"""Cache hierarchy: invalidation correctness (the stale-read oracle),
LRU eviction under memory pressure, caches-off equivalence, version
counters through the connector SPI, and the observability surfaces
(EXPLAIN ANALYZE + system.runtime.caches). See docs/CACHING.md."""

import pytest


@pytest.fixture()
def fresh_caches():
    from presto_tpu.cache import reset_cache_manager
    reset_cache_manager()
    yield
    reset_cache_manager()


@pytest.fixture()
def runner(fresh_caches):
    from presto_tpu.runner import LocalRunner
    return LocalRunner("memory", "default")


# ---------------------------------------------------------------------------
# stale-read oracle: write -> repeat query must reflect the write


def test_insert_invalidates_repeat_query(runner):
    runner.execute("create table t as select 1 a, 10 b")
    q = "select sum(b) from t"
    assert runner.execute(q).rows() == [(10,)]
    assert runner.execute(q).rows() == [(10,)]  # warm the caches
    runner.execute("insert into t values (2, 32)")
    assert runner.execute(q).rows() == [(42,)]


def test_ctas_after_drop_invalidates(runner):
    runner.execute("create table t as select 5 x")
    q = "select x from t"
    assert runner.execute(q).rows() == [(5,)]
    assert runner.execute(q).rows() == [(5,)]
    runner.execute("drop table t")
    runner.execute("create table t as select 7 x")
    assert runner.execute(q).rows() == [(7,)]


def test_drop_evicts_dependent_entries(runner):
    from presto_tpu.cache import get_cache_manager
    runner.execute("create table t as select 1 x")
    runner.execute("select x from t")
    runner.execute("select x from t")
    mgr = get_cache_manager()
    assert len(mgr.plan) > 0
    runner.execute("drop table t")
    # eager cross-level invalidation at the DDL commit point
    assert all(("memory", "default", "t") not in
               getattr(e, "deps", ())
               for e in mgr.fragment._entries.values())
    assert runner.execute(
        "select count(*) from system.runtime.caches").rows() == [(3,)]


def test_table_version_bumps_on_writes(runner):
    handle_md = runner.catalogs.connector("memory").metadata
    from presto_tpu.connectors.spi import TableHandle
    h = TableHandle("memory", "default", "t")
    runner.execute("create table t as select 1 a")
    v0 = handle_md.table_version(h)
    runner.execute("insert into t values (2)")
    v1 = handle_md.table_version(h)
    assert v1 > v0
    runner.execute("drop table t")
    assert handle_md.table_version(h) is None


def test_sqlite_version_and_stale_read(tmp_path, fresh_caches):
    from presto_tpu.connectors.sqlite import SqliteConnector
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    conn = SqliteConnector(str(tmp_path / "c.db"))
    r.register_connector("db", conn)
    r.execute("create table db.main.t as select 1 a, 2 b")
    q = "select sum(b) from db.main.t"
    assert r.execute(q).rows() == [(2,)]
    v0 = conn.metadata.table_version(
        __import__("presto_tpu.connectors.spi",
                   fromlist=["TableHandle"]).TableHandle(
            "db", "main", "t"))
    r.execute("insert into db.main.t values (3, 40)")
    assert r.execute(q).rows() == [(42,)]
    assert conn.metadata.table_version(
        __import__("presto_tpu.connectors.spi",
                   fromlist=["TableHandle"]).TableHandle(
            "db", "main", "t")) > v0


def test_file_connector_stale_read(tmp_path, monkeypatch,
                                   fresh_caches):
    monkeypatch.setenv("PRESTO_TPU_FILE_ROOT", str(tmp_path))
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    r.execute("create table file.lake.t as "
              "select nationkey, name from nation")
    q = "select count(*) from file.lake.t"
    assert r.execute(q).rows() == [(25,)]
    assert r.execute(q).rows() == [(25,)]
    r.execute("insert into file.lake.t values (99, 'X')")
    assert r.execute(q).rows() == [(26,)]


# ---------------------------------------------------------------------------
# caches-off equivalence: every cached result byte-identical


TPCH_EQUIV = [
    "select returnflag, linestatus, sum(quantity) q, "
    "count(*) c from lineitem group by returnflag, linestatus "
    "order by returnflag, linestatus",
    "select count(*) from orders where orderkey < 1000",
    "select n.name, count(*) c from nation n "
    "join customer cu on cu.nationkey = n.nationkey "
    "group by n.name order by n.name",
]


def test_caches_off_equivalence(fresh_caches):
    from presto_tpu.runner import LocalRunner
    on = LocalRunner("tpch", "tiny")
    off = LocalRunner("tpch", "tiny", {
        "plan_cache_enabled": False,
        "fragment_result_cache_enabled": False,
        "page_source_cache_enabled": False})
    for sql in TPCH_EQUIV:
        cold = on.execute(sql).rows()
        warm = on.execute(sql).rows()   # plan+fragment+page hits
        warm2 = on.execute(sql).rows()
        plain = off.execute(sql).rows()
        assert cold == warm == warm2 == plain, sql


def test_disabled_levels_take_no_entries(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny", {
        "plan_cache_enabled": False,
        "fragment_result_cache_enabled": False,
        "page_source_cache_enabled": False})
    r.execute("select count(*) from region")
    r.execute("select count(*) from region")
    mgr = get_cache_manager(create=False)
    if mgr is not None:
        assert len(mgr.plan) == 0
        assert len(mgr.fragment) == 0
        assert len(mgr.page) == 0


# ---------------------------------------------------------------------------
# LRU eviction under memory pressure


def test_lru_eviction_under_memory_pressure(fresh_caches):
    from presto_tpu.batch import Batch
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.types import BIGINT
    mgr = get_cache_manager({"cache_memory_bytes": 1 << 20})
    from presto_tpu.execution.memory import batch_bytes
    b = Batch.from_pydict({"x": (list(range(4096)), BIGINT)})
    per = batch_bytes(b)
    n = (1 << 20) // per + 4  # guaranteed past the budget
    for i in range(n):
        assert mgr.page.put(("k", i), [b], [("c", "s", "t")])
    assert mgr.page.stats.evictions > 0
    assert mgr.pool.reserved <= 1 << 20
    assert len(mgr.page) < n
    # LRU order: the newest entries survive, the oldest went first
    assert mgr.page.get(("k", n - 1)) is not None
    assert mgr.page.get(("k", 0)) is None


def test_query_path_respects_budget(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    budget = 256 << 10
    r = LocalRunner("tpch", "tiny",
                    {"cache_memory_bytes": budget})
    for _ in range(2):
        r.execute("select sum(quantity) from lineitem")
        r.execute("select sum(extendedprice) from lineitem")
        r.execute("select count(*) from orders where orderkey > 0")
    mgr = get_cache_manager()
    assert mgr.pool.budget == budget
    assert mgr.pool.reserved <= budget
    # correctness survives the pressure
    assert r.execute("select sum(quantity) from lineitem").rows() == \
        LocalRunner("tpch", "tiny", {
            "page_source_cache_enabled": False,
            "fragment_result_cache_enabled": False,
        }).execute("select sum(quantity) from lineitem").rows()


def test_oversized_entry_not_cached(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    mgr = get_cache_manager({"cache_memory_bytes": 1 << 20})
    import numpy as np
    from presto_tpu.batch import Batch
    from presto_tpu.types import BIGINT
    big = Batch.from_pydict(
        {"x": (list(range(100_000)), BIGINT)})
    assert mgr.fragment.put("k", [big], []) is False
    assert len(mgr.fragment) == 0


# ---------------------------------------------------------------------------
# isolation: same-named tables of DIFFERENT connector instances


def test_no_cross_runner_collision(fresh_caches):
    from presto_tpu.runner import LocalRunner
    a = LocalRunner("memory", "default")
    b = LocalRunner("memory", "default")
    a.execute("create table t as select 1 x")
    b.execute("create table t as select 2 x")
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]
    # warm both, then again — still isolated
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]


def test_volatile_system_tables_never_cached(fresh_caches):
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    q = "select count(*) from system.runtime.queries"
    n0 = r.execute(q).rows()[0][0]
    n1 = r.execute(q).rows()[0][0]
    assert n1 == n0 + 1  # each execution observes the previous one


# ---------------------------------------------------------------------------
# observability + toggles


def test_explain_analyze_shows_cache_counters(fresh_caches):
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    sql = ("select regionkey, count(*) from nation "
           "group by regionkey order by regionkey")
    r.execute(sql)  # record
    res = r.execute("explain analyze " + sql)
    text = "\n".join(row[0] for row in res.rows())
    assert "fragment_replay" in text
    assert "cache: 1 hits" in text


def test_system_runtime_caches_counters(fresh_caches):
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    sql = "select count(*) from supplier"
    r.execute(sql)
    r.execute(sql)
    rows = r.execute(
        "select level, hits, misses from system.runtime.caches "
        "order by level").rows()
    by_level = {lvl: (h, m) for lvl, h, m in rows}
    assert set(by_level) == {"plan", "fragment", "page"}
    assert by_level["plan"][0] >= 1
    assert by_level["fragment"][0] >= 1


def test_set_session_toggles_levels(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    sql = "select count(*) from part"
    r.execute(sql)
    r.execute("set session plan_cache_enabled = false")
    r.execute("set session fragment_result_cache_enabled = false")
    r.execute("set session page_source_cache_enabled = false")
    mgr = get_cache_manager()
    h0 = (mgr.plan.stats.hits, mgr.fragment.stats.hits,
          mgr.page.stats.hits)
    r.execute(sql)
    assert (mgr.plan.stats.hits, mgr.fragment.stats.hits,
            mgr.page.stats.hits) == h0


def test_prepared_statement_plan_cache(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    r.execute("prepare p1 from select count(*) from nation "
              "where regionkey = ?")
    assert r.execute("execute p1 using 1").rows() == [(5,)]
    assert r.execute("execute p1 using 1").rows() == [(5,)]
    mgr = get_cache_manager()
    assert mgr.plan.stats.hits >= 1
    # re-PREPARE under the same name must not serve the old plan
    r.execute("deallocate prepare p1")
    r.execute("prepare p1 from select count(*) from nation "
              "where regionkey <> ?")
    assert r.execute("execute p1 using 1").rows() == [(20,)]


def test_width_retry_replans_through_cache(fresh_caches):
    """array_agg width overflow bumps a session property — the retry
    must MISS the plan cache (the width is baked into plan forms)."""
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny", {"array_agg_width": 2})
    rows = r.execute(
        "select regionkey, array_agg(nationkey) a from nation "
        "group by regionkey order by regionkey").rows()
    assert len(rows) == 5
    assert sorted(rows[0][1]) == [0, 5, 14, 15, 16]


def test_plan_cache_preserves_literal_whitespace(fresh_caches):
    """normalize_sql must NOT collapse whitespace inside string
    literals — two queries differing only there have different
    answers, and aliasing them would serve wrong results."""
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    assert r.execute("select 'x  y' v").rows() == [("x  y",)]
    assert r.execute("select 'x y' v").rows() == [("x y",)]
    assert r.execute("select 'x  y' v").rows() == [("x  y",)]
    # outside-literal whitespace still normalizes to one key
    from presto_tpu.cache import normalize_sql
    assert normalize_sql("select  1 ;") == normalize_sql("select 1")
    assert normalize_sql("select 'a  b'") != normalize_sql(
        "select 'a b'")
    assert normalize_sql('select "c  d" from t') != normalize_sql(
        'select "c d" from t')
    assert normalize_sql("select 'it''s  ok'") != normalize_sql(
        "select 'it''s ok'")


def test_plan_cache_no_cross_runner_eviction_pingpong(fresh_caches):
    """Two runners' same-named memory tables (different connector
    instances) must coexist in the plan cache as distinct misses —
    token mismatch is NOT staleness and must not evict the peer."""
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    a = LocalRunner("memory", "default")
    b = LocalRunner("memory", "default")
    a.execute("create table t as select 1 x")
    b.execute("create table t as select 2 x")
    a.execute("select x from t")
    b.execute("select x from t")
    mgr = get_cache_manager()
    ev0 = mgr.plan.stats.evictions
    # alternate lookups: both runners must HIT their own entries
    h0 = mgr.plan.stats.hits
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]
    assert mgr.plan.stats.evictions == ev0
    assert mgr.plan.stats.hits >= h0 + 2
