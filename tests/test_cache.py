"""Cache hierarchy: invalidation correctness (the stale-read oracle),
LRU eviction under memory pressure, caches-off equivalence, version
counters through the connector SPI, and the observability surfaces
(EXPLAIN ANALYZE + system.runtime.caches). See docs/CACHING.md."""

import pytest


@pytest.fixture()
def fresh_caches():
    from presto_tpu.cache import reset_cache_manager
    reset_cache_manager()
    yield
    reset_cache_manager()


@pytest.fixture()
def runner(fresh_caches):
    from presto_tpu.runner import LocalRunner
    return LocalRunner("memory", "default")


# ---------------------------------------------------------------------------
# stale-read oracle: write -> repeat query must reflect the write


def test_insert_invalidates_repeat_query(runner):
    runner.execute("create table t as select 1 a, 10 b")
    q = "select sum(b) from t"
    assert runner.execute(q).rows() == [(10,)]
    assert runner.execute(q).rows() == [(10,)]  # warm the caches
    runner.execute("insert into t values (2, 32)")
    assert runner.execute(q).rows() == [(42,)]


def test_ctas_after_drop_invalidates(runner):
    runner.execute("create table t as select 5 x")
    q = "select x from t"
    assert runner.execute(q).rows() == [(5,)]
    assert runner.execute(q).rows() == [(5,)]
    runner.execute("drop table t")
    runner.execute("create table t as select 7 x")
    assert runner.execute(q).rows() == [(7,)]


def test_drop_evicts_dependent_entries(runner):
    from presto_tpu.cache import get_cache_manager
    runner.execute("create table t as select 1 x")
    runner.execute("select x from t")
    runner.execute("select x from t")
    mgr = get_cache_manager()
    assert len(mgr.plan) > 0
    runner.execute("drop table t")
    # eager cross-level invalidation at the DDL commit point
    assert all(("memory", "default", "t") not in
               getattr(e, "deps", ())
               for e in mgr.fragment._entries.values())
    assert runner.execute(
        "select count(*) from system.runtime.caches").rows() == [(3,)]


def test_table_version_bumps_on_writes(runner):
    handle_md = runner.catalogs.connector("memory").metadata
    from presto_tpu.connectors.spi import TableHandle
    h = TableHandle("memory", "default", "t")
    runner.execute("create table t as select 1 a")
    v0 = handle_md.table_version(h)
    runner.execute("insert into t values (2)")
    v1 = handle_md.table_version(h)
    assert v1 > v0
    runner.execute("drop table t")
    assert handle_md.table_version(h) is None


def test_sqlite_version_and_stale_read(tmp_path, fresh_caches):
    from presto_tpu.connectors.sqlite import SqliteConnector
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    conn = SqliteConnector(str(tmp_path / "c.db"))
    r.register_connector("db", conn)
    r.execute("create table db.main.t as select 1 a, 2 b")
    q = "select sum(b) from db.main.t"
    assert r.execute(q).rows() == [(2,)]
    v0 = conn.metadata.table_version(
        __import__("presto_tpu.connectors.spi",
                   fromlist=["TableHandle"]).TableHandle(
            "db", "main", "t"))
    r.execute("insert into db.main.t values (3, 40)")
    assert r.execute(q).rows() == [(42,)]
    assert conn.metadata.table_version(
        __import__("presto_tpu.connectors.spi",
                   fromlist=["TableHandle"]).TableHandle(
            "db", "main", "t")) > v0


def test_file_connector_stale_read(tmp_path, monkeypatch,
                                   fresh_caches):
    monkeypatch.setenv("PRESTO_TPU_FILE_ROOT", str(tmp_path))
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    r.execute("create table file.lake.t as "
              "select nationkey, name from nation")
    q = "select count(*) from file.lake.t"
    assert r.execute(q).rows() == [(25,)]
    assert r.execute(q).rows() == [(25,)]
    r.execute("insert into file.lake.t values (99, 'X')")
    assert r.execute(q).rows() == [(26,)]


# ---------------------------------------------------------------------------
# caches-off equivalence: every cached result byte-identical


TPCH_EQUIV = [
    "select returnflag, linestatus, sum(quantity) q, "
    "count(*) c from lineitem group by returnflag, linestatus "
    "order by returnflag, linestatus",
    "select count(*) from orders where orderkey < 1000",
    "select n.name, count(*) c from nation n "
    "join customer cu on cu.nationkey = n.nationkey "
    "group by n.name order by n.name",
]


def test_caches_off_equivalence(fresh_caches):
    from presto_tpu.runner import LocalRunner
    on = LocalRunner("tpch", "tiny")
    off = LocalRunner("tpch", "tiny", {
        "plan_cache_enabled": False,
        "fragment_result_cache_enabled": False,
        "page_source_cache_enabled": False})
    for sql in TPCH_EQUIV:
        cold = on.execute(sql).rows()
        warm = on.execute(sql).rows()   # plan+fragment+page hits
        warm2 = on.execute(sql).rows()
        plain = off.execute(sql).rows()
        assert cold == warm == warm2 == plain, sql


def test_disabled_levels_take_no_entries(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny", {
        "plan_cache_enabled": False,
        "fragment_result_cache_enabled": False,
        "page_source_cache_enabled": False})
    r.execute("select count(*) from region")
    r.execute("select count(*) from region")
    mgr = get_cache_manager(create=False)
    if mgr is not None:
        assert len(mgr.plan) == 0
        assert len(mgr.fragment) == 0
        assert len(mgr.page) == 0


# ---------------------------------------------------------------------------
# LRU eviction under memory pressure


def test_lru_eviction_under_memory_pressure(fresh_caches):
    from presto_tpu.batch import Batch
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.types import BIGINT
    mgr = get_cache_manager({"cache_memory_bytes": 1 << 20})
    from presto_tpu.execution.memory import batch_bytes
    b = Batch.from_pydict({"x": (list(range(4096)), BIGINT)})
    per = batch_bytes(b)
    n = (1 << 20) // per + 4  # guaranteed past the budget
    for i in range(n):
        assert mgr.page.put(("k", i), [b], [("c", "s", "t")])
    assert mgr.page.stats.evictions > 0
    assert mgr.pool.reserved <= 1 << 20
    assert len(mgr.page) < n
    # LRU order: the newest entries survive, the oldest went first
    assert mgr.page.get(("k", n - 1)) is not None
    assert mgr.page.get(("k", 0)) is None


def test_query_path_respects_budget(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    budget = 256 << 10
    r = LocalRunner("tpch", "tiny",
                    {"cache_memory_bytes": budget})
    for _ in range(2):
        r.execute("select sum(quantity) from lineitem")
        r.execute("select sum(extendedprice) from lineitem")
        r.execute("select count(*) from orders where orderkey > 0")
    mgr = get_cache_manager()
    assert mgr.pool.budget == budget
    assert mgr.pool.reserved <= budget
    # correctness survives the pressure
    assert r.execute("select sum(quantity) from lineitem").rows() == \
        LocalRunner("tpch", "tiny", {
            "page_source_cache_enabled": False,
            "fragment_result_cache_enabled": False,
        }).execute("select sum(quantity) from lineitem").rows()


def test_oversized_entry_not_cached(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    mgr = get_cache_manager({"cache_memory_bytes": 1 << 20})
    import numpy as np
    from presto_tpu.batch import Batch
    from presto_tpu.types import BIGINT
    big = Batch.from_pydict(
        {"x": (list(range(100_000)), BIGINT)})
    assert mgr.fragment.put("k", [big], []) is False
    assert len(mgr.fragment) == 0


# ---------------------------------------------------------------------------
# isolation: same-named tables of DIFFERENT connector instances


def test_no_cross_runner_collision(fresh_caches):
    from presto_tpu.runner import LocalRunner
    a = LocalRunner("memory", "default")
    b = LocalRunner("memory", "default")
    a.execute("create table t as select 1 x")
    b.execute("create table t as select 2 x")
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]
    # warm both, then again — still isolated
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]


def test_volatile_system_tables_never_cached(fresh_caches):
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    q = "select count(*) from system.runtime.queries"
    n0 = r.execute(q).rows()[0][0]
    n1 = r.execute(q).rows()[0][0]
    assert n1 == n0 + 1  # each execution observes the previous one


# ---------------------------------------------------------------------------
# observability + toggles


def test_explain_analyze_shows_cache_counters(fresh_caches):
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    sql = ("select regionkey, count(*) from nation "
           "group by regionkey order by regionkey")
    r.execute(sql)  # record
    res = r.execute("explain analyze " + sql)
    text = "\n".join(row[0] for row in res.rows())
    assert "fragment_replay" in text
    assert "cache: 1 hits" in text


def test_system_runtime_caches_counters(fresh_caches):
    from presto_tpu.runner import LocalRunner
    # history off: its store generation is PART of the plan-cache
    # key by design (a material commit re-plans once) — these tests
    # assert raw plan-cache hit mechanics across exactly two runs
    r = LocalRunner("tpch", "tiny",
                    {"history_based_optimization": False})
    sql = "select count(*) from supplier"
    r.execute(sql)
    r.execute(sql)
    rows = r.execute(
        "select level, hits, misses from system.runtime.caches "
        "order by level").rows()
    by_level = {lvl: (h, m) for lvl, h, m in rows}
    assert set(by_level) == {"plan", "fragment", "page"}
    assert by_level["plan"][0] >= 1
    assert by_level["fragment"][0] >= 1


def test_set_session_toggles_levels(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    sql = "select count(*) from part"
    r.execute(sql)
    r.execute("set session plan_cache_enabled = false")
    r.execute("set session fragment_result_cache_enabled = false")
    r.execute("set session page_source_cache_enabled = false")
    mgr = get_cache_manager()
    h0 = (mgr.plan.stats.hits, mgr.fragment.stats.hits,
          mgr.page.stats.hits)
    r.execute(sql)
    assert (mgr.plan.stats.hits, mgr.fragment.stats.hits,
            mgr.page.stats.hits) == h0


def test_prepared_statement_plan_cache(fresh_caches):
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    # history off: its store generation is PART of the plan-cache
    # key by design (a material commit re-plans once) — these tests
    # assert raw plan-cache hit mechanics across exactly two runs
    r = LocalRunner("tpch", "tiny",
                    {"history_based_optimization": False})
    r.execute("prepare p1 from select count(*) from nation "
              "where regionkey = ?")
    assert r.execute("execute p1 using 1").rows() == [(5,)]
    assert r.execute("execute p1 using 1").rows() == [(5,)]
    mgr = get_cache_manager()
    assert mgr.plan.stats.hits >= 1
    # re-PREPARE under the same name must not serve the old plan
    r.execute("deallocate prepare p1")
    r.execute("prepare p1 from select count(*) from nation "
              "where regionkey <> ?")
    assert r.execute("execute p1 using 1").rows() == [(20,)]


def test_width_retry_replans_through_cache(fresh_caches):
    """array_agg width overflow bumps a session property — the retry
    must MISS the plan cache (the width is baked into plan forms)."""
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny", {"array_agg_width": 2})
    rows = r.execute(
        "select regionkey, array_agg(nationkey) a from nation "
        "group by regionkey order by regionkey").rows()
    assert len(rows) == 5
    assert sorted(rows[0][1]) == [0, 5, 14, 15, 16]


def test_plan_cache_preserves_literal_whitespace(fresh_caches):
    """normalize_sql must NOT collapse whitespace inside string
    literals — two queries differing only there have different
    answers, and aliasing them would serve wrong results."""
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    assert r.execute("select 'x  y' v").rows() == [("x  y",)]
    assert r.execute("select 'x y' v").rows() == [("x y",)]
    assert r.execute("select 'x  y' v").rows() == [("x  y",)]
    # outside-literal whitespace still normalizes to one key
    from presto_tpu.cache import normalize_sql
    assert normalize_sql("select  1 ;") == normalize_sql("select 1")
    assert normalize_sql("select 'a  b'") != normalize_sql(
        "select 'a b'")
    assert normalize_sql('select "c  d" from t') != normalize_sql(
        'select "c d" from t')
    assert normalize_sql("select 'it''s  ok'") != normalize_sql(
        "select 'it''s ok'")


def test_plan_cache_no_cross_runner_eviction_pingpong(fresh_caches):
    """Two runners' same-named memory tables (different connector
    instances) must coexist in the plan cache as distinct misses —
    token mismatch is NOT staleness and must not evict the peer."""
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.runner import LocalRunner
    # history off: its store generation is PART of the plan-cache
    # key by design (a material commit re-plans once) — these tests
    # assert raw plan-cache hit mechanics across exactly two runs
    a = LocalRunner("memory", "default",
                    {"history_based_optimization": False})
    b = LocalRunner("memory", "default",
                    {"history_based_optimization": False})
    a.execute("create table t as select 1 x")
    b.execute("create table t as select 2 x")
    a.execute("select x from t")
    b.execute("select x from t")
    mgr = get_cache_manager()
    ev0 = mgr.plan.stats.evictions
    # alternate lookups: both runners must HIT their own entries
    h0 = mgr.plan.stats.hits
    assert a.execute("select x from t").rows() == [(1,)]
    assert b.execute("select x from t").rows() == [(2,)]
    assert mgr.plan.stats.evictions == ev0
    assert mgr.plan.stats.hits >= h0 + 2


def test_normalize_sql_is_comment_aware(fresh_caches):
    """A `--` comment ends at ITS newline: collapsing that newline
    into a space would let the comment swallow the following tokens
    and alias two queries with different answers (a false hit).
    Reviewed end-to-end: the 3-row query must not poison the key of
    the 1-row query."""
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("memory", "default")
    r.execute(
        "create table t as select * from (values (1), (2), (3)) v(x)")
    assert len(r.execute("SELECT 1 --x\nFROM t").rows()) == 3
    # semantically `SELECT 1` — everything after -- is comment
    assert len(r.execute("SELECT 1 --x FROM t").rows()) == 1

    from presto_tpu.cache import normalize_sql
    # comments are token separators, never token glue
    assert normalize_sql("SELECT 1 --x\nFROM t") != normalize_sql(
        "SELECT 1 --x FROM t")
    assert normalize_sql("SELECT 1 --x FROM t") == normalize_sql(
        "SELECT 1")
    # comment variants of one statement share a key (more hits,
    # same semantics)
    assert normalize_sql("select/*c*/1") == normalize_sql("select 1")
    assert normalize_sql("select 1 -- trailing") == normalize_sql(
        "select 1")
    assert normalize_sql("select /* a\nb */ 1") == normalize_sql(
        "select 1")
    # comment markers inside quotes are DATA, not comments
    assert normalize_sql("select '--x' v") != normalize_sql(
        "select '' v")
    assert normalize_sql("select '/*x*/' v") != normalize_sql(
        "select '' v")
    # unterminated block comment (a LexError at parse time) must not
    # alias a valid statement
    assert normalize_sql("select 1 /*x") != normalize_sql("select 1")
    # token-derived keys: keyword/identifier case normalizes away,
    # but only ONE trailing semicolon (what the grammar accepts) drops
    assert normalize_sql("SELECT x FROM T") == normalize_sql(
        "select x from t")
    assert normalize_sql('select "Q" from t') != normalize_sql(
        'select "q" from t')
    assert normalize_sql("select 1;;") != normalize_sql("select 1")


def test_execute_as_isolates_session_properties(fresh_caches):
    """The per-request identity path carries a COPY of the properties
    dict, so one HTTP client can't mutate planner/cache behavior for
    every other user of the shared single-node runner — and because
    that copy dies with the request, SET/RESET SESSION reject loudly
    instead of returning success with no effect."""
    from presto_tpu.runner import LocalRunner
    from presto_tpu.runner.local import QueryError
    r = LocalRunner("memory", "default")
    for user in ("alice", ""):  # the default user is isolated too
        with pytest.raises(QueryError, match="per-request"):
            r.execute_as("set session batch_rows = 128", user)
        with pytest.raises(QueryError, match="per-request"):
            r.execute_as("reset session batch_rows", user)
        assert "batch_rows" not in r.session.properties
    # queries still run under the per-request identity
    r.execute("create table t as select 1 x")
    assert r.execute_as("select x from t", "alice").rows() == [(1,)]
    # the embedded (non-request) path keeps durable SET SESSION
    r.execute("set session batch_rows = 128")
    assert r.session.properties["batch_rows"] == 128


def test_unhashable_access_control_keys_on_minted_token(fresh_caches):
    """Unhashable policies get a minted token stamped on the object
    (nothing pinned process-wide — the old id()+pin scheme leaked one
    object per policy forever); distinct policies never share keys."""
    from presto_tpu.execution.access_control import (
        AccessControlManager,
    )
    from presto_tpu.runner import LocalRunner

    class UnhashablePolicy(AccessControlManager):
        def __eq__(self, other):  # kills hashability
            return self is other
        __hash__ = None

    # history off: the store generation inside the session key would
    # make the exactly-two-run hit assertion below miss once by design
    a = LocalRunner("memory", "default",
                    {"history_based_optimization": False},
                    access_control=UnhashablePolicy())
    b = LocalRunner("memory", "default",
                    {"history_based_optimization": False},
                    access_control=UnhashablePolicy())
    ka = a._session_cache_key()
    kb = b._session_cache_key()
    assert ka is not None and kb is not None and ka != kb
    # stable across calls (token minted once, stamped on the policy)
    assert a._session_cache_key() == ka
    # plan caching still works end-to-end under such a policy
    a.execute("create table t as select 1 x")
    assert a.execute("select x from t").rows() == [(1,)]
    assert a.execute("select x from t").rows() == [(1,)]
    from presto_tpu.cache import get_cache_manager
    assert get_cache_manager().plan.stats.hits >= 1


def test_split_token_rejects_default_repr():
    """An unhashable split payload whose repr falls back to
    object.__repr__ identifies by ADDRESS — unstable across runs and
    reusable after GC (a recycled address could serve another split's
    pages). Such splits are uncacheable, not trusted."""
    from presto_tpu.cache import split_token

    class Split:
        def __init__(self, info):
            self.info = info
            self.partition = 0

    class Opaque:  # unhashable, default repr
        __hash__ = None

    assert split_token(Split(Opaque())) is None
    assert split_token(Split([Opaque()])) is None  # nested too
    # unhashable but value-rendering payloads stay cacheable
    t = split_token(Split({"path": "f.orc", "row": 5}))
    assert t is not None
    assert t == split_token(Split({"path": "f.orc", "row": 5}))
    # hashable payloads keep first-class identity
    assert split_token(Split(("f.orc", 5))) == (("f.orc", 5), 0)


def test_rule_mutation_invalidates_cached_plan(fresh_caches):
    """Appending a revoke to the policy's in-place rules list must
    change the plan-cache key: cached plans skip the analysis-time
    access checks, so a key holding only the policy INSTANCE would
    keep serving a revoked user until eviction."""
    from presto_tpu.execution.access_control import (
        AccessControlManager, AccessRule,
    )
    from presto_tpu.runner import LocalRunner
    from presto_tpu.runner.local import QueryError
    ac = AccessControlManager([])
    r = LocalRunner("memory", "default", user="bob",
                    access_control=ac)
    r.execute("create table secret as select 1 x")
    assert r.execute("select x from secret").rows() == [(1,)]
    assert r.execute("select x from secret").rows() == [(1,)]  # warm
    ac.rules.append(AccessRule(user="bob", table="secret",
                               allow_select=False))
    with pytest.raises(QueryError, match="cannot select"):
        r.execute("select x from secret")
    # and lifting the revoke works again (key moves back)
    ac.rules.pop()
    assert r.execute("select x from secret").rows() == [(1,)]


def test_put_rejects_instead_of_raising_on_reserve_race(
        fresh_caches, monkeypatch):
    """A best-effort cache insert must never fail the caller's query:
    if a concurrent budget shrink makes pool.reserve throw after the
    fit check, put() counts a rejection and returns False."""
    from presto_tpu.batch import Batch
    from presto_tpu.cache import get_cache_manager
    from presto_tpu.execution.memory import MemoryLimitExceeded
    from presto_tpu.types import BIGINT
    mgr = get_cache_manager({"cache_memory_bytes": 1 << 20})

    def boom(tag, nbytes):
        raise MemoryLimitExceeded(tag, nbytes, 0, 0)

    monkeypatch.setattr(mgr.pool, "reserve", boom)
    b = Batch.from_pydict({"x": ([1], BIGINT)})
    assert mgr.fragment.put(("k",), [b]) is False
    assert mgr.fragment.stats.rejected == 1
    assert len(mgr.fragment) == 0
