"""Parquet storage layer (storage/parquet.py; reference:
presto-parquet ParquetReader + OrcSelectiveRecordReader's pushdown
pruning) and the file connector over it. pyarrow is used ONLY to
verify interoperability with standard writers/readers."""

import numpy as np
import pytest

from presto_tpu.storage import parquet as pq


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_FILE_ROOT", str(tmp_path))
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpch", "tiny")


def test_roundtrip_own_files(tmp_path):
    cols = [pq.ParquetColumn("a", pq.T_INT64, optional=False),
            pq.ParquetColumn("b", pq.T_DOUBLE),
            pq.ParquetColumn("s", pq.T_BYTE_ARRAY, pq.CONV_UTF8)]
    n = 500
    data = {"a": np.arange(n, dtype=np.int64),
            "b": np.linspace(0, 1, n),
            "s": [f"v{i % 13}".encode() for i in range(n)]}
    masks = {"b": np.arange(n) % 5 != 0,
             "s": np.arange(n) % 7 != 0}
    path = str(tmp_path / "t.parquet")
    for codec in (pq.CODEC_UNCOMPRESSED, pq.CODEC_GZIP):
        pq.write_table(path, cols, data, masks, codec=codec,
                       row_group_rows=200)
        info = pq.read_footer(path)
        assert info.num_rows == n
        assert len(info.row_groups) == 3
        vals, mask = [], []
        for g in info.row_groups:
            v, m = pq.read_column(path, g, "b")
            vals.append(v)
            mask.append(m)
        m = np.concatenate(mask)
        assert (m == masks["b"]).all()
        assert np.allclose(np.concatenate(vals),
                           data["b"][masks["b"]])


def test_row_group_statistics(tmp_path):
    cols = [pq.ParquetColumn("k", pq.T_INT64, optional=False)]
    path = str(tmp_path / "s.parquet")
    pq.write_table(path, cols,
                   {"k": np.arange(1000, dtype=np.int64)},
                   row_group_rows=250)
    info = pq.read_footer(path)
    assert pq.group_min_max(info.row_groups[0], "k") == (0, 249)
    assert pq.group_min_max(info.row_groups[3], "k") == (750, 999)


def test_pyarrow_reads_our_file(tmp_path):
    papq = pytest.importorskip("pyarrow.parquet")
    cols = [pq.ParquetColumn("x", pq.T_INT64, optional=False),
            pq.ParquetColumn("y", pq.T_BYTE_ARRAY, pq.CONV_UTF8)]
    n = 100
    path = str(tmp_path / "ours.parquet")
    pq.write_table(path, cols, {
        "x": np.arange(n, dtype=np.int64),
        "y": [f"s{i}".encode() for i in range(n)],
    }, {"y": np.arange(n) % 3 != 0}, codec=pq.CODEC_GZIP)
    t = papq.read_table(path)
    assert t.column("x").to_pylist() == list(range(n))
    got = t.column("y").to_pylist()
    assert got[0] is None and got[1] == "s1"


def test_we_read_pyarrow_file(tmp_path):
    pa = pytest.importorskip("pyarrow")
    papq = pytest.importorskip("pyarrow.parquet")
    n = 300
    tbl = pa.table({
        "x": pa.array(list(range(n)), pa.int64()),
        "y": pa.array([None if i % 4 == 0 else f"v{i % 11}"
                       for i in range(n)]),
    })
    path = str(tmp_path / "arrow.parquet")
    # dictionary + gzip: the encodings arrow uses by default
    papq.write_table(tbl, path, compression="GZIP")
    info = pq.read_footer(path)
    x, _ = pq.read_column(path, info.row_groups[0], "x")
    assert list(x) == list(range(n))
    y, ym = pq.read_column(path, info.row_groups[0], "y")
    assert list(y) == [f"v{i % 11}".encode() for i in range(n)
                       if i % 4 != 0]
    assert (~ym[::4]).all()


def test_ctas_and_query_through_sql(runner):
    """CTAS into the file catalog writes Parquet; scans read it back
    with full SQL (joins, aggregation, NULL handling)."""
    runner.execute(
        "create table file.default.items as "
        "select orderkey, partkey, quantity, returnflag, shipdate "
        "from lineitem")
    res = runner.execute(
        "select returnflag, count(*) c, sum(quantity) q "
        "from file.default.items group by returnflag "
        "order by returnflag")
    want = runner.execute(
        "select returnflag, count(*) c, sum(quantity) q "
        "from lineitem group by returnflag order by returnflag")
    assert res.rows() == want.rows()
    # join parquet back against a generated table
    res2 = runner.execute(
        "select count(*) from file.default.items i, orders o "
        "where i.orderkey = o.orderkey and o.orderdate >= "
        "date '1995-01-01'")
    want2 = runner.execute(
        "select count(*) from lineitem l, orders o "
        "where l.orderkey = o.orderkey and o.orderdate >= "
        "date '1995-01-01'")
    assert res2.rows() == want2.rows()


def test_show_and_drop(runner):
    runner.execute("create table file.default.tiny_nation as "
                   "select * from nation")
    assert "tiny_nation" in [
        r[0] for r in runner.execute(
            "show tables from file.default").rows()]
    rows = runner.execute(
        "select name, regionkey from file.default.tiny_nation "
        "order by name limit 3").rows()
    assert rows[0][0] == "ALGERIA"
    runner.execute("drop table file.default.tiny_nation")
    assert "tiny_nation" not in [
        r[0] for r in runner.execute(
            "show tables from file.default").rows()]


def test_insert_into_existing(runner):
    """INSERT INTO an existing parquet table rewrites the file with
    old + new rows (immutable files, transactional swap)."""
    runner.execute("create table file.default.nat2 as "
                   "select nationkey, name from nation "
                   "where nationkey < 3")
    runner.execute("insert into file.default.nat2 "
                   "select nationkey, name from nation "
                   "where nationkey >= 23")
    rows = runner.execute("select nationkey, name from "
                          "file.default.nat2 order by nationkey").rows()
    assert [r[0] for r in rows] == [0, 1, 2, 23, 24]
    assert rows[-1][1] == "UNITED STATES"
    runner.execute("drop table file.default.nat2")


def test_row_group_pruning(runner, tmp_path):
    """A pushed-down range predicate skips row groups whose min/max
    can't match — verified by counting rows actually materialized."""
    import os
    root = os.environ["PRESTO_TPU_FILE_ROOT"]
    os.makedirs(os.path.join(root, "default"), exist_ok=True)
    cols = [pq.ParquetColumn("k", pq.T_INT64, optional=False),
            pq.ParquetColumn("v", pq.T_DOUBLE, optional=False)]
    n = 4000
    pq.write_table(os.path.join(root, "default", "pruned.parquet"),
                   cols,
                   {"k": np.arange(n, dtype=np.int64),
                    "v": np.arange(n, dtype=np.float64)},
                   row_group_rows=1000)
    res = runner.execute("select count(*), min(k), max(k) "
                         "from file.default.pruned where k >= 3500")
    assert res.rows() == [(500, 3500, 3999)]
    # pruning observable via connector-level scan
    conn = runner.catalogs.connector("file")
    from presto_tpu.connectors.spi import Domain, TableHandle, \
        TupleDomain
    handle = TableHandle("file", "default", "pruned")
    splits = conn.split_manager.get_splits(handle, 1)
    dom = TupleDomain((("k", Domain(low=3500)),))
    batches = list(conn.page_source.batches(
        splits[0], ["k"], 1 << 20, dom))
    total_capacity_rows = sum(int(b.num_valid()) for b in batches)
    assert total_capacity_rows == 1000  # 3 of 4 groups pruned
