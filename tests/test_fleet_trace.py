"""Fleet-wide distributed tracing: one merged Perfetto timeline
spanning the coordinator + 2 SUBPROCESS workers, with a retried
task's dead attempt AND its replacement both visible (the tentpole's
acceptance shape, kept to one lean subprocess battery — tier-1 budget
is tight)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest


def _spawn_worker(extra_env=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
           **(extra_env or {})}
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.server.node",
         "--port", "0"],
        cwd="/root/repo", env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = json.loads(proc.stdout.readline())["url"]
    return proc, url


@pytest.fixture(scope="module")
def traced_fleet():
    """Coordinator + 2 subprocess workers; worker A is env-armed with
    ONE executor.quantum fault, so exactly one task attempt dies
    mid-execution and the fault-tolerant scheduler retries it —
    deterministic, no process killing, both attempts' spans survive."""
    workers = []
    try:
        proc_a, url_a = _spawn_worker(
            {"PRESTO_TPU_FAULTS": "executor.quantum:once"})
        workers.append(proc_a)
        proc_b, url_b = _spawn_worker()
        workers.append(proc_b)
        from presto_tpu.server.coordinator import Coordinator
        coord = Coordinator(
            [url_a, url_b], "tpch", "tiny",
            properties={"query_trace_enabled": True,
                        "task_retries": 2},
            heartbeat_interval_s=0.25)
        coord.start()
        coord.check_workers()
        yield coord, url_a, url_b
    finally:
        try:
            coord.stop()
        except Exception:  # noqa: BLE001
            pass
        for w in workers:
            w.send_signal(signal.SIGTERM)
        for w in workers:
            try:
                w.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.kill()


def test_merged_timeline_with_retried_attempt(traced_fleet):
    coord, url_a, url_b = traced_fleet
    from presto_tpu.runner import LocalRunner
    sql = ("select returnflag, count(*), sum(extendedprice) "
           "from lineitem group by returnflag order by returnflag")
    result = coord.execute(sql)
    rows = result.rows()

    # correctness first: byte-equal to a local run despite the
    # injected mid-task death
    want = LocalRunner("tpch", "tiny").execute(sql).rows()
    assert len(rows) == len(want)
    for g, w in zip(rows, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert abs(g[2] - w[2]) < 1e-6 * max(abs(w[2]), 1)

    # the injected fault actually fired on worker A (vacuity guard)
    from presto_tpu.server.node import http_get
    info_a = json.loads(http_get(f"{url_a}/v1/info"))
    assert info_a.get("faults", {}).get(
        "executor.quantum", {}).get("fired", 0) >= 1

    # the task-retry tier absorbed it
    report = getattr(result, "task_report", None)
    assert report and report["retried"] >= 1, report

    events = result.trace_events
    assert events, "traced query must carry its merged timeline"

    # ONE document spans coordinator + both workers: pid 1 is the
    # coordinator recorder, each worker got its own pid with a
    # process_name metadata record
    pids = {e.get("pid") for e in events if isinstance(e.get("pid"),
                                                       int)}
    worker_pids = {p for p in pids if p >= 2}
    assert 1 in pids and len(worker_pids) == 2, pids
    names = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert any(url_a in n for n in names)
    assert any(url_b in n for n in names)

    # worker-side spans from both lanes are present (the workers each
    # recorded their task's drive — kernel/operator/task spans)
    by_pid = {}
    for e in events:
        if e.get("ph") == "X" and e.get("pid", 1) >= 2:
            by_pid.setdefault(e["pid"], []).append(e["name"])
    assert len(by_pid) == 2, by_pid.keys()
    assert all(any(n == "task" for n in v) for v in by_pid.values())

    # the RETRIED task is visible twice: coordinator-side attempt
    # lanes exist for attempt 1 (failed) and attempt 2 of one slot
    attempts = {}
    for e in events:
        n = e.get("name", "")
        if e.get("cat") == "task" and " attempt " in n:
            base, _, att = n.rpartition(" attempt ")
            attempts.setdefault(base, set()).add(att)
    retried = {b: a for b, a in attempts.items() if len(a) >= 2}
    assert retried, attempts
    # the dead attempt's lane closed with a non-finished state
    failed_states = [e["args"].get("state") for e in events
                     if e.get("cat") == "task"
                     and isinstance(e.get("args"), dict)
                     and e["args"].get("state")
                     not in (None, "finished")]
    assert failed_states, "dead attempt must be visible with its state"

    # timestamps are clock-offset adjusted: every worker span must
    # land INSIDE a window around the query's own span (the offsets
    # were applied; raw epochs would be wildly outside)
    qspans = [e for e in events
              if e.get("name") == "query" and e.get("ph") == "X"]
    assert qspans
    q0 = min(e["ts"] for e in qspans)
    q1 = max(e["ts"] + e["dur"] for e in qspans)
    margin = (q1 - q0) * 2 + 2_000_000  # 2s slack in us
    for e in events:
        if e.get("ph") == "X" and e.get("pid", 1) >= 2:
            assert q0 - margin <= e["ts"] <= q1 + margin, e

    # the document loads as chrome trace JSON (sanity round-trip)
    json.loads(json.dumps({"traceEvents": events}))


def test_task_trace_drain_endpoint(traced_fleet):
    """GET /v1/task/{id}/trace drains a live task's spans; the
    terminal status ships only the remainder (exercised against a
    finished task: the drain returns [] after status shipped them)."""
    coord, url_a, url_b = traced_fleet
    coord.execute("select count(*) from region")
    from presto_tpu.server.node import http_get
    for url in (url_a, url_b):
        tasks = json.loads(http_get(f"{url}/v1/tasks"))
        for tid in tasks:
            doc = json.loads(http_get(f"{url}/v1/task/{tid}/trace"))
            assert "traceEvents" in doc


def test_two_worker_critical_path_sums_to_wall(traced_fleet):
    """The 2-worker topology pin of the critical-path invariant: the
    blocking chain extracted from a merged fleet timeline (per-worker
    pids, clock-offset-shifted remote lanes) must still partition the
    root wall within the stated tolerance, and the coordinator must
    have attached the doc to the query's stats."""
    coord, url_a, url_b = traced_fleet
    from presto_tpu.telemetry import critical_path as cp
    result = coord.execute(
        "select count(*), sum(extendedprice) from lineitem "
        "where quantity > 10")
    events = result.trace_events
    assert events
    doc = cp.extract(events)
    assert doc is not None
    ok, detail = cp.verify(doc, tolerance=0.05)
    assert ok, detail
    # remote lanes contributed: at least one blocking segment must
    # come from a worker pid span name recorded worker-side
    assert doc["segments"]

    # the HTTP surface: a traced statement's GET /v1/query/{id} body
    # carries stats.critical_path (computed at query finish)
    from presto_tpu.server.coordinator import StatementClient
    from presto_tpu.server.node import http_get
    c = StatementClient(coord.url, user="cp-test")
    known = set(coord.queries)
    c.execute("select count(*) from orders where totalprice > 1000")
    qid = next(i for i in coord.queries if i not in known)
    row = json.loads(http_get(f"{coord.url}/v1/query/{qid}"))
    cp_doc = (row.get("stats") or {}).get("critical_path")
    assert cp_doc is not None
    ok, detail = cp.verify(cp_doc, tolerance=0.05)
    assert ok, detail
