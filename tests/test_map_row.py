"""MAP and ROW expression values (reference: common/type/MapType +
RowType, operator/scalar/MapFunctions), lowered at analysis time like
the fixed-width arrays they are built from."""

import pytest

from test_tpch_suite import runner  # noqa: F401 (fixture)


CASES = {
    "map_subscript": (
        "select map(array['a','b'], array[1,2])['b']", [(2,)]),
    "element_at": (
        "select element_at(map(array[1,2,3], array['x','y','z']), 2)",
        [("y",)]),
    "missing_key_null": (
        "select element_at(map(array[1,2], array['x','y']), 99)",
        [(None,)]),
    "cardinality": (
        "select cardinality(map(array[1,2,3], array[4,5,6]))",
        [(3,)]),
    "map_keys": (
        "select element_at(map_keys(map(array[10,20], "
        "array['a','b'])), 2)", [(20,)]),
    "map_values": (
        "select element_at(map_values(map(array[10,20], "
        "array['a','b'])), 1)", [("a",)]),
    "dynamic_keys_from_split": (
        "select map(split('a,b,c', ','), array[1,2,3])['c']", [(3,)]),
    "transform_values_lambda": (
        "select transform_values(map(array['a','b'], array[1,2]), "
        "(k, v) -> v * 10)['b']", [(20,)]),
    "row_field": (
        "select row(1, 'x', 2.5)[2]", [("x",)]),
    "row_numeric_field": (
        "select row(1, 'x', 2.5)[3] * 2", [(5.0,)]),
    "map_over_column": (
        # a tiny decode table applied per row (the dimension-lookup
        # idiom maps replace)
        "select count(*) from lineitem where "
        "map(array['A','N','R'], array[1,2,3])[returnflag] = 2",
        None),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_map_row(name, runner):  # noqa: F811
    sql, expected = CASES[name]
    got = runner.execute(sql).rows()
    if expected is None:
        want = runner.execute(
            "select count(*) from lineitem "
            "where returnflag = 'N'").rows()
        assert got == want
    else:
        assert got == expected, (sql, got)


def test_map_dynamic_value_array_bounds(runner):  # noqa: F811
    """A dynamic value array caps the ENTRY count: padding slots past
    its real length are not map entries (deviation noted in
    _resolve_map_fn: the reference raises on runtime size mismatch;
    we take the pairwise min)."""
    got = runner.execute(
        "select cardinality(map(array[1,2,3], split('x', ','))), "
        "element_at(map(array[1,2,3], split('x', ',')), 2)").rows()
    assert got == [(1, None)], got


def test_map_row_errors(runner):  # noqa: F811
    from presto_tpu.runner.local import QueryError
    with pytest.raises(QueryError, match="differ in size"):
        runner.execute("select map(array[1,2], array['x'])[1]")
    with pytest.raises(QueryError, match="out of range"):
        runner.execute("select row(1, 2)[5]")
    with pytest.raises(QueryError, match="constant integer"):
        runner.execute("select row(1, 2)['x']")
    # round 5: complex values PROJECT as columns now (exploded slot
    # representation, nodes.Field.form)
    assert runner.execute(
        "select map(array[1], array[2])").rows() == [({1: 2},)]
    assert runner.execute("select row(1, 2)").rows() == [((1, 2),)]
