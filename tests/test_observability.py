"""Coordinator observability surface (reference:
server/QueryResource.java:49, the webapp/ status UI, and
spi/eventlistener/EventListener + EventListenerManager.java)."""

import json

import pytest

from test_distributed import cluster, local_rows  # noqa: F401


def _get(url):
    from presto_tpu.server.node import http_get
    return http_get(url, timeout=30)


def test_query_resource_lists_queries(cluster):  # noqa: F811
    from presto_tpu.server.coordinator import StatementClient
    StatementClient(cluster.url, user="alice").execute(
        "select count(*) from nation")
    rows = json.loads(_get(f"{cluster.url}/v1/query"))
    assert rows and any(r["user"] == "alice"
                        and r["state"] == "FINISHED" for r in rows)
    qid = next(r["id"] for r in rows if r["user"] == "alice")
    detail = json.loads(_get(f"{cluster.url}/v1/query/{qid}"))
    assert detail["sql"].startswith("select count(*)")
    assert detail["columns"]


def test_resource_groups_endpoint(cluster):  # noqa: F811
    snap = json.loads(_get(f"{cluster.url}/v1/resourceGroups"))
    assert any(g["group"] == "root" for g in snap)
    assert {"running", "queued", "hard_concurrency"} <= set(snap[0])


def test_ui_page_renders(cluster):  # noqa: F811
    page = _get(f"{cluster.url}/ui").decode()
    assert "<html" in page and "presto-tpu coordinator" in page
    assert "workers (" in page and "resource groups" in page
    # worker table shows the registered workers as active
    for url in cluster.worker_urls:
        assert url in page


def test_event_listeners_fire_and_cannot_fail_queries(cluster):  # noqa: F811
    from presto_tpu.server.coordinator import StatementClient
    events = []

    def bad_listener(_):
        raise RuntimeError("observer bug")
    cluster.event_listeners.append(events.append)
    cluster.event_listeners.append(bad_listener)
    try:
        _, rows = StatementClient(cluster.url, user="bob").execute(
            "select count(*) from region")
        assert rows == [[5]]
        kinds = [e["event"] for e in events
                 if e.get("user") == "bob"]
        assert kinds == ["query_created", "query_completed"]
        done = next(e for e in events
                    if e.get("user") == "bob"
                    and e["event"] == "query_completed")
        assert done["state"] == "FINISHED"
        assert done["rows"] == 1
        assert done["elapsed_ms"] > 0
    finally:
        cluster.event_listeners.clear()
