"""Memory + blackhole connectors and predicate pushdown (reference:
presto-memory TestMemoryConnector / presto-blackhole tests, and the
TupleDomain pushdown seam through ConnectorPageSourceProvider)."""

import pytest


@pytest.fixture()
def runner():
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpch", "tiny")


def test_ctas_select_roundtrip(runner):
    runner.execute(
        "create table memory.default.nations_ge10 as "
        "select nationkey, name, regionkey from nation "
        "where nationkey >= 10")
    got = runner.execute(
        "select nationkey, name from memory.default.nations_ge10 "
        "order by nationkey").rows()
    want = runner.execute(
        "select nationkey, name from nation where nationkey >= 10 "
        "order by nationkey").rows()
    assert got == want and len(got) > 0


def test_ctas_join_back(runner):
    """Memory tables participate in joins/aggregations like any scan."""
    runner.execute("create table memory.default.cust as "
                   "select custkey, nationkey, acctbal from customer")
    got = runner.execute(
        "select n.name, count(*) c from memory.default.cust c "
        "join nation n on c.nationkey = n.nationkey "
        "group by n.name order by c desc, n.name limit 3").rows()
    want = runner.execute(
        "select n.name, count(*) c from customer c "
        "join nation n on c.nationkey = n.nationkey "
        "group by n.name order by c desc, n.name limit 3").rows()
    assert got == want


def test_insert_append_and_nulls(runner):
    runner.execute("create table memory.default.t as "
                   "select nationkey, name from nation "
                   "where nationkey < 3")
    runner.execute("insert into memory.default.t "
                   "select nationkey, name from nation "
                   "where nationkey between 3 and 4")
    # column-subset insert: name gets NULL
    runner.execute("insert into memory.default.t (nationkey) "
                   "select nationkey from nation where nationkey = 5")
    rows = runner.execute("select nationkey, name from "
                          "memory.default.t order by nationkey").rows()
    assert len(rows) == 6
    assert rows[-1] == (5, None)
    assert rows[0][1] is not None


def test_insert_string_dictionary_growth(runner):
    """Appends with unseen strings re-encode onto a unified
    dictionary; scans and predicates stay consistent."""
    runner.execute("create table memory.default.seg as "
                   "select mktsegment from customer "
                   "where nationkey < 5")
    runner.execute("insert into memory.default.seg "
                   "values ('ZZZ_NEW_SEGMENT')")
    rows = runner.execute(
        "select mktsegment, count(*) from memory.default.seg "
        "group by mktsegment order by mktsegment").rows()
    assert rows[-1] == ("ZZZ_NEW_SEGMENT", 1)
    one = runner.execute(
        "select count(*) from memory.default.seg "
        "where mktsegment = 'ZZZ_NEW_SEGMENT'").rows()
    assert one == [(1,)]


def test_insert_type_mismatch(runner):
    from presto_tpu.runner import QueryError
    runner.execute("create table memory.default.x as "
                   "select nationkey from nation")
    with pytest.raises(QueryError, match="type mismatch"):
        runner.execute("insert into memory.default.x "
                       "select name from nation")


def test_drop_table(runner):
    from presto_tpu.runner import QueryError
    runner.execute("create table memory.default.d as "
                   "select 1 a")
    runner.execute("drop table memory.default.d")
    with pytest.raises(QueryError, match="does not exist"):
        runner.execute("select * from memory.default.d")
    runner.execute("drop table if exists memory.default.d")
    with pytest.raises(QueryError, match="does not exist"):
        runner.execute("drop table memory.default.d")


def test_ctas_if_not_exists(runner):
    runner.execute("create table memory.default.e as select 1 a")
    runner.execute(
        "create table if not exists memory.default.e as select 2 a")
    assert runner.execute(
        "select a from memory.default.e").rows() == [(1,)]


def test_blackhole_sink(runner):
    runner.execute("create table blackhole.default.sink as "
                   "select * from lineitem")
    conn = runner.catalogs.connector("blackhole")
    assert conn.written_rows("default", "sink") > 5000
    # reads come back empty (write-throughput sink)
    assert runner.execute(
        "select count(*) from blackhole.default.sink").rows() == [(0,)]


def test_tpch_scan_honors_pushdown(runner):
    """The pushed TupleDomain shrinks what the tpch connector
    generates and transfers, without changing results."""
    from presto_tpu.connectors.spi import Domain, TupleDomain
    conn = runner.catalogs.connector("tpch")
    from presto_tpu.connectors.spi import TableHandle
    handle = TableHandle("tpch", "tiny", "orders")
    [split] = conn.split_manager.get_splits(handle, 1)
    full = sum(b.num_valid() for b in conn.page_source.batches(
        split, ["orderkey", "orderdate"], 1 << 16))
    lo = 9800  # ~1996-11 as epoch days
    td = TupleDomain((("orderdate", Domain(low=lo)),))
    pruned = sum(b.num_valid() for b in conn.page_source.batches(
        split, ["orderkey", "orderdate"], 1 << 16, td))
    assert 0 < pruned < full / 2


def test_pushdown_plan_and_results(runner):
    """The optimizer attaches the constraint; results are unchanged
    (the engine keeps its filter — pushdown is unenforced)."""
    from presto_tpu.planner import nodes as N
    from presto_tpu.planner.optimizer import optimize
    plan = optimize(runner.create_plan(
        "select count(*) from orders "
        "where orderdate >= date '1996-01-01' and orderkey > 100"))

    scans = []

    def walk(n):
        if isinstance(n, N.TableScanNode):
            scans.append(n)
        for s in n.sources():
            walk(s)
    walk(plan)
    [scan] = scans
    assert scan.constraint is not None
    cols = [c for c, _ in scan.constraint.domains]
    assert "orderdate" in cols and "orderkey" in cols
    got = runner.execute(
        "select count(*), sum(orderkey) from orders "
        "where orderdate >= date '1996-01-01' and orderkey > 100").rows()
    # cross-check against the unfiltered arithmetic on pandas
    df = runner.catalogs.connector("tpch").table_pandas("tiny", "orders")
    sel = df[(df.orderdate >= 9496) & (df.orderkey > 100)]
    assert got == [(len(sel), int(sel.orderkey.sum()))]


def test_declared_sort_orders_hold(runner):
    """Every sorted_by declaration must match generator output — the
    streaming-aggregation operator's carry protocol silently corrupts
    groups on unsorted input (advisor r4: partsupp declared
    [partkey, suppkey] while suppkey wraps modulo nsupp)."""
    import numpy as np
    conn = runner.catalogs.connector("tpch")
    gen = conn._gens["tiny"]
    md = conn.metadata
    for table in ("orders", "lineitem", "customer", "part", "supplier",
                  "nation", "region", "partsupp"):
        handle = type("H", (), {"schema": "tiny", "table": table})()
        order = md.sorted_by(handle)
        assert order, table
        data = gen.generate(table, 0, gen.rows(table))
        cols = [np.asarray(data[c]) for c in order]
        # lexicographic non-decreasing check across the declared keys
        rank = np.zeros(len(cols[0]) - 1, dtype=bool)  # strictly-less seen
        ok = np.ones(len(cols[0]) - 1, dtype=bool)
        for c in cols:
            a, b = c[:-1], c[1:]
            ok &= rank | (a <= b)
            rank = rank | (a < b)
        assert ok.all(), f"{table} not sorted by {order}"
