"""Full TPC-H Q1-Q22 correctness vs a sqlite oracle over identical data
(reference analog: AbstractTestQueries' H2-checked battery,
presto-tests/AbstractTestQueryFramework.java:71 — our H2 is sqlite3).

The engine runs the canonical query text (tests/tpch_queries.py); the
oracle runs a sqlite-dialect translation over the same generated rows
(dates stored as ISO strings)."""

import datetime
import math
import re
import sqlite3

import numpy as np
import pytest

from tpch_queries import QUERIES

SCHEMA = "tiny"
DATE_COLS = {
    "lineitem": ["shipdate", "commitdate", "receiptdate"],
    "orders": ["orderdate"],
}
EPOCH = datetime.date(1970, 1, 1)


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpch", SCHEMA)


@pytest.fixture(scope="module")
def oracle(runner):
    conn = runner.catalogs.connector("tpch")
    db = sqlite3.connect(":memory:")
    for table in ["lineitem", "orders", "customer", "supplier", "nation",
                  "region", "part", "partsupp"]:
        df = conn.table_pandas(SCHEMA, table)
        for c in DATE_COLS.get(table, []):
            df[c] = [(EPOCH + datetime.timedelta(days=int(d))).isoformat()
                     for d in df[c]]
        df.to_sql(table, db, index=False)
    return db


def to_sqlite(sql: str) -> str:
    sql = re.sub(r"date\s+'([0-9-]+)'", r"'\1'", sql)
    sql = re.sub(r"extract\s*\(\s*year\s+from\s+([A-Za-z0-9_.]+)\s*\)",
                 r"CAST(strftime('%Y', \1) AS INTEGER)", sql)
    return sql


def normalize(rows, types):
    out = []
    for row in rows:
        vals = []
        for v, t in zip(row, types):
            if v is None:
                vals.append(None)
            elif t == "date" and isinstance(v, int):
                vals.append((EPOCH + datetime.timedelta(days=v))
                            .isoformat())
            elif isinstance(v, float):
                vals.append(v)
            else:
                vals.append(v)
        out.append(tuple(vals))
    return out


def assert_rows_equal(got, exp, qn, ordered):
    assert len(got) == len(exp), \
        f"Q{qn}: {len(got)} rows != oracle {len(exp)}"
    if not ordered:
        got = sorted(got, key=str)
        exp = sorted(exp, key=str)
    for i, (g, e) in enumerate(zip(got, exp)):
        assert len(g) == len(e), f"Q{qn} row {i}: arity"
        for j, (gv, ev) in enumerate(zip(g, e)):
            if gv is None or ev is None:
                assert gv is None and ev is None, \
                    f"Q{qn} row {i} col {j}: {gv!r} != {ev!r}"
            elif isinstance(gv, float) or isinstance(ev, float):
                assert math.isclose(float(gv), float(ev), rel_tol=1e-6,
                                    abs_tol=1e-6), \
                    f"Q{qn} row {i} col {j}: {gv!r} != {ev!r}"
            else:
                assert gv == ev, f"Q{qn} row {i} col {j}: {gv!r} != {ev!r}"


#: queries whose final ORDER BY fully determines row order (no ties
#: possible on the tiny dataset) -> compared ordered; the rest compared
#: as sorted multisets
FULLY_ORDERED = {1, 4, 5, 7, 8, 9, 12, 15, 16, 22}


#: Q2 and Q21 are the battery's two heaviest compiles (~8-10s each on
#: the 2-core host); they ride the slow tier, the other 20 stay fast.
@pytest.mark.parametrize("qn", [
    qn if qn not in (2, 21) else pytest.param(qn, marks=pytest.mark.slow)
    for qn in sorted(QUERIES)])
def test_tpch_query(qn, runner, oracle):
    res = runner.execute(QUERIES[qn])
    types = [f.type.name for f in res.fields]
    got = normalize(res.rows(), types)
    cur = oracle.execute(to_sqlite(QUERIES[qn]))
    exp = [tuple(r) for r in cur.fetchall()]
    assert_rows_equal(got, exp, qn, qn in FULLY_ORDERED)
