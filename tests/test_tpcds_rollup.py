"""Canonical ROLLUP-based TPC-DS query shapes (q27, q67 spine) —
sqlite has no ROLLUP, so the oracle runs the equivalent UNION ALL
expansion over the same generated rows."""

import sqlite3

import pytest

from test_tpch_suite import assert_rows_equal, normalize


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpcds", "tiny")


@pytest.fixture(scope="module")
def oracle(runner):
    conn = runner.catalogs.connector("tpcds")
    db = sqlite3.connect(":memory:")
    for t in ["store_sales", "date_dim", "item", "store",
              "customer_demographics"]:
        conn.table_pandas("tiny", t).to_sql(t, db, index=False)
    return db


def test_q27_shape(runner, oracle):
    """q27: demographic item averages with s_state rollup."""
    got = runner.execute("""
        select i_item_id, s_state, grouping(i_item_id, s_state) g,
               avg(ss_quantity) agg1, avg(ss_list_price) agg2
        from store_sales, customer_demographics, date_dim, store, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
          and cd_gender = 'M' and d_year = 2000
        group by rollup(i_item_id, s_state)
        order by i_item_id, s_state
        limit 200""").rows()
    base = """
        from store_sales, customer_demographics, date_dim, store, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
          and cd_gender = 'M' and d_year = 2000"""
    exp = [tuple(r) for r in oracle.execute(f"""
        select * from (
          select i_item_id, s_state, 0 g, avg(ss_quantity) a1,
                 avg(ss_list_price) a2 {base}
          group by i_item_id, s_state
          union all
          select i_item_id, null, 1, avg(ss_quantity),
                 avg(ss_list_price) {base} group by i_item_id
          union all
          select null, null, 3, avg(ss_quantity),
                 avg(ss_list_price) {base})
        order by i_item_id nulls last, s_state nulls last limit 200""").fetchall()]
    assert_rows_equal(
        normalize(got, ["varchar", "varchar", "bigint", "double",
                        "double"]), exp, "q27", False)


def test_q67_shape(runner, oracle):
    """q67 spine: category/class/brand rollup of sales totals."""
    got = runner.execute("""
        select i_category, i_class, i_brand,
               sum(ss_ext_sales_price) s
        from store_sales, date_dim, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and d_moy = 11
        group by rollup(i_category, i_class, i_brand)
        order by i_category, i_class, i_brand
        limit 100""").rows()
    base = """
        from store_sales, date_dim, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and d_moy = 11"""
    exp = [tuple(r) for r in oracle.execute(f"""
        select * from (
          select i_category, i_class, i_brand,
                 sum(ss_ext_sales_price) {base}
          group by i_category, i_class, i_brand
          union all
          select i_category, i_class, null,
                 sum(ss_ext_sales_price) {base}
          group by i_category, i_class
          union all
          select i_category, null, null,
                 sum(ss_ext_sales_price) {base} group by i_category
          union all
          select null, null, null, sum(ss_ext_sales_price) {base})
        order by i_category nulls last, i_class nulls last, i_brand nulls last limit 100""").fetchall()]
    assert_rows_equal(
        normalize(got, ["varchar"] * 3 + ["double"]), exp, "q67",
        False)
