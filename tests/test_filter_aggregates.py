"""FILTER (WHERE ...) on aggregates (reference: the SQL standard
filtered-aggregate clause the reference's AccumulatorCompiler masks
support) — contributions gate per call; groups still form from the
full row set; distributed, the filter applies at the PARTIAL step."""

import sqlite3

import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpch", "tiny")


@pytest.fixture(scope="module")
def oracle(runner):
    db = sqlite3.connect(":memory:")
    runner.catalogs.connector("tpch").table_pandas(
        "tiny", "lineitem").to_sql("lineitem", db, index=False)
    return db


SQL = """
select returnflag,
       count(*) filter (where quantity > 25) big,
       sum(quantity) filter (where linestatus = 'O') sum_open,
       avg(discount) filter (where discount > 0.05) hi_disc,
       count(*) total
from lineitem group by returnflag order by returnflag
"""


def assert_match(got, exp):
    assert len(got) == len(exp)
    for g, e in zip(got, exp):
        for gv, ev in zip(g, e):
            if gv is None or ev is None:
                assert gv is None and ev is None, (g, e)
            elif isinstance(gv, float):
                assert abs(gv - ev) < 1e-9, (g, e)
            else:
                assert gv == ev, (g, e)


def test_filter_vs_oracle(runner, oracle):
    got = runner.execute(SQL).rows()
    exp = [tuple(r) for r in oracle.execute(SQL).fetchall()]
    assert_match(got, exp)
    # empty-filter groups: SUM over no contributions is NULL, the
    # group itself still appears
    assert got[0][2] is None and got[0][4] > 0


@pytest.mark.slow
def test_filter_distributed(runner):
    from presto_tpu.runner import MeshRunner
    assert MeshRunner("tpch", "tiny").execute(SQL).rows() \
        == runner.execute(SQL).rows()


def test_filter_global_agg(runner, oracle):
    sql = ("select count(*) filter (where quantity > 40), "
           "sum(quantity) from lineitem")
    got = runner.execute(sql).rows()
    exp = [tuple(r) for r in oracle.execute(sql).fetchall()]
    assert_match(got, exp)


def test_filter_with_distinct_rejected(runner):
    from presto_tpu.runner.local import QueryError
    with pytest.raises(QueryError, match="FILTER"):
        runner.execute(
            "select count(distinct linestatus) "
            "filter (where quantity > 10) from lineitem")
