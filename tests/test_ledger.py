"""Wall-clock attribution ledger (telemetry/ledger.py): the coverage
invariant, the compile/dispatch/device_wait mutual-exclusion oracle,
and every surface the residual is served on (EXPLAIN ANALYZE,
system.runtime.queries, Prometheus)."""

import json
import time

import pytest


@pytest.fixture()
def runner():
    from presto_tpu.runner import LocalRunner
    return LocalRunner("tpch", "tiny")


def _mix_queries():
    import sys
    sys.path.insert(0, "/root/repo/tests")
    from tpch_queries import QUERIES
    return {n: QUERIES[n] for n in (1, 3, 6, 13)}


# ---------------------------------------------------------------------------
# unit: self-time nesting


def test_span_self_time_nesting():
    """A nested span's wall subtracts from its parent's SELF time, and
    leaf adds subtract from the enclosing frame — categories can never
    double-count within a thread."""
    from presto_tpu.telemetry import ledger
    led = ledger.QueryLedger()
    prev = ledger.install(led)
    try:
        t0 = time.perf_counter_ns()
        with ledger.span("driver"):
            time.sleep(0.01)
            with ledger.span("scan"):
                time.sleep(0.01)
            ledger.add("dispatch", 3_000_000)  # 3ms leaf
        wall = time.perf_counter_ns() - t0
    finally:
        ledger.uninstall(prev)
    snap = led.snapshot()
    assert snap["scan"] >= 9_000_000
    assert snap["dispatch"] == 3_000_000
    # driver got ONLY its self time: total minus scan minus the leaf
    assert snap["driver"] >= 9_000_000 - 3_000_000
    total = sum(snap.values())
    # no double counting: the categories sum to <= elapsed wall
    assert total <= wall + 1_000_000
    doc = led.finish(wall)
    ledger.verify_coverage(doc)
    assert doc["unattributed_ms"] >= -0.01


def test_uninstalled_thread_is_noop():
    from presto_tpu.telemetry import ledger
    assert ledger.current() is None
    ledger.add("scan", 1_000_000)  # must not raise
    with ledger.span("driver"):
        pass


# ---------------------------------------------------------------------------
# oracle: cold compile / warm dispatch / device_wait are mutually
# exclusive (the async-dispatch undercount satellite)


def test_kernel_oracle_compile_dispatch_device_wait_exclusive():
    """A deterministic FakeJit: its first call grows the jit cache
    (compile), later calls don't (dispatch); a drain-point wait is a
    device_wait span. Each nanosecond lands in EXACTLY one category —
    the invariant holds with zero residual double-count."""
    from presto_tpu.telemetry import kernels as tk
    from presto_tpu.telemetry import ledger

    class FakeJit:
        def __init__(self):
            self.n = 0
            self.compile_next = True

        def _cache_size(self):
            return self.n

        def __call__(self, x):
            if self.compile_next:
                self.compile_next = False
                self.n += 1
                time.sleep(0.01)
            else:
                time.sleep(0.002)
            return x

    fake = FakeJit()
    wrapped = tk.instrument_kernel(fake, "ledger_oracle_fake",
                                   jits=[fake])
    led = ledger.QueryLedger()
    prev = ledger.install(led)
    try:
        t0 = time.perf_counter_ns()
        wrapped(1)            # cold: compile
        wrapped(2)            # warm: dispatch
        with ledger.span("device_wait"):
            time.sleep(0.005)  # drain-point wait
        wall = time.perf_counter_ns() - t0
    finally:
        ledger.uninstall(prev)
    snap = led.snapshot()
    assert snap["compile"] >= 9_000_000
    assert snap["dispatch"] >= 1_000_000
    assert snap["device_wait"] >= 4_000_000
    # mutual exclusion: compile's wall is NOT also in dispatch or
    # device_wait — the three sum to no more than elapsed wall
    assert snap["compile"] + snap["dispatch"] + snap["device_wait"] \
        <= wall
    doc = led.finish(wall)
    ledger.verify_coverage(doc)
    assert doc["unattributed_ms"] >= 0.0


# ---------------------------------------------------------------------------
# integration: the serving mix


def test_serving_mix_coverage_invariant(runner):
    """Every mix query's ledger must satisfy Σ categories +
    unattributed == wall with a small, NON-NEGATIVE residual — the
    machine check behind the <10% acceptance bar (asserted loosely
    here: tiny-schema walls are ms-scale, the bench asserts the real
    bar at sf0_1)."""
    from presto_tpu.telemetry.ledger import verify_coverage
    for name, sql in _mix_queries().items():
        res = runner.execute(sql)
        doc = res.query_stats["ledger"]
        verify_coverage(doc)
        assert doc["unattributed_ms"] >= -1.0, (name, doc)
        assert doc["unattributed_frac"] < 0.6, (name, doc)
        assert doc["categories_ms"], (name, doc)


def test_warm_run_has_dispatch_not_compile(runner):
    sql = "select count(*) from lineitem where quantity < 10"
    runner.execute(sql)
    warm = runner.execute(sql).query_stats["ledger"]
    assert warm["categories_ms"].get("compile", 0.0) == 0.0, warm
    assert warm["categories_ms"].get("dispatch", 0.0) > 0.0, warm


def test_explain_analyze_renders_attribution(runner):
    res = runner.execute(
        "explain analyze select count(*) from orders")
    text = "\n".join(r[0] for r in res.rows())
    assert "wall attribution" in text
    assert "unattributed" in text
    # every category line carries ms + percent columns
    assert "driver" in text


def test_system_runtime_queries_unattributed(runner):
    runner.execute("select count(*) from region")
    rows = runner.execute(
        "select query_id, state, unattributed_ms "
        "from system.runtime.queries order by query_id").rows()
    finished = [r for r in rows if r[1] == "FINISHED"]
    assert finished
    # a finished query's residual is a real (>= 0) measurement; the
    # observing in-flight query reports the -1 sentinel
    assert finished[0][2] >= 0.0
    assert rows[-1][1] == "RUNNING" and rows[-1][2] == -1.0


def test_ledger_metrics_and_histogram(runner):
    from presto_tpu.telemetry.metrics import METRICS
    before_ns = METRICS.total("presto_tpu_ledger_ns_total")
    h_before = METRICS.histogram_snapshot(
        "presto_tpu_ledger_unattributed_ratio")["count"]
    runner.execute("select count(*) from nation")
    assert METRICS.total("presto_tpu_ledger_ns_total") > before_ns
    h = METRICS.histogram_snapshot(
        "presto_tpu_ledger_unattributed_ratio")
    assert h["count"] == h_before + 1
    # render includes the histogram exposition triplet
    rendered = METRICS.render()
    assert "presto_tpu_ledger_unattributed_ratio_bucket" in rendered
    assert "presto_tpu_ledger_unattributed_ratio_count" in rendered


# ---------------------------------------------------------------------------
# the doctor


def test_query_doctor_verdicts():
    from presto_tpu.tools.query_doctor import diagnose

    def doc(cats, wall):
        unattr = wall - sum(cats.values())
        return {"wall_ms": wall, "categories_ms": cats,
                "unattributed_ms": unattr,
                "unattributed_frac": unattr / wall}

    assert diagnose(doc({"queued": 800.0, "dispatch": 50.0},
                        1000.0))["verdict"] == "queueing"
    assert diagnose(doc({"compile": 500.0, "device_wait": 200.0,
                         "scan": 100.0},
                        900.0))["verdict"] == "kernel"
    assert diagnose(doc({"serde": 300.0, "exchange": 300.0,
                         "dispatch": 100.0},
                        800.0))["verdict"] == "exchange"
    # unattributed residual counts as GLUE — host time nobody
    # attributed finer is host glue by definition
    assert diagnose(doc({"scan": 300.0, "driver": 200.0},
                        1000.0))["verdict"] == "glue"


def test_query_doctor_end_to_end(runner, tmp_path):
    from presto_tpu.tools import query_doctor
    res = runner.execute("select count(*) from customer")
    f = tmp_path / "stats.json"
    f.write_text(json.dumps({"stats": res.query_stats}))
    assert query_doctor.main(["--file", str(f)]) == 0
    assert query_doctor.main(["--file", str(f), "--json"]) == 0
