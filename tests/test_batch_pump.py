"""Batch-pump battery (operators/driver.py): the pipelined data
plane's fast path must be invisible except in the clock.

Oracles: (1) byte-identity — every query answers identically pump-on
vs pump-off (serving mix fast, the full TPC-H suite in the slow lane);
(2) lifecycle — cancel and deadline land mid-pump at quantum
boundaries, and the `executor.quantum` chaos site fires under a
pumping driver; (3) zero new kernels — the pump re-uses the exact
kernel families the pair loop compiled (it moves batches differently,
it must not compute differently)."""

import threading
import time

import pytest

from presto_tpu.execution.task_executor import (
    TaskExecutor, set_task_executor,
)
from presto_tpu.operators import driver as driver_mod
from presto_tpu.runner.local import LocalRunner, QueryError
from presto_tpu.telemetry.metrics import METRICS

NO_CACHE = {"plan_cache_enabled": False,
            "fragment_result_cache_enabled": False,
            "page_source_cache_enabled": False}

#: small batches => many splits through the pump, so lifecycle events
#: land mid-stream instead of racing a single-split query
SLOW_PROPS = {**NO_CACHE, "batch_rows": 1024}

#: pump-ELIGIBLE shape (scan -> agg fold -> emit): the lifecycle
#: tests below must land their events inside the pump fast path, so
#: the query has to take it
SQL_AGG = ("select returnflag, count(*) c, sum(quantity) q "
           "from lineitem group by returnflag")

#: join + blocking sort: every driver shape here (build sink, probe
#: chain, sort-terminated final) is in the widened streamable set
SQL_JOIN = ("select o.orderpriority, count(*) c "
            "from orders o join customer c on o.custkey = c.custkey "
            "group by o.orderpriority order by o.orderpriority")


@pytest.fixture
def pump_state():
    """Restore the process-wide pump switch after each test."""
    prev = driver_mod.pump_enabled()
    yield
    driver_mod.set_pump(prev)


@pytest.fixture
def small_executor():
    ex = TaskExecutor(workers=2, quantum_ms=5,
                      level_thresholds_s=(0.0, 0.01, 0.05, 0.2, 1.0))
    prev = set_task_executor(ex)
    yield ex
    set_task_executor(prev)
    ex.shutdown()


def _pumped(n0: float) -> bool:
    return METRICS.get("presto_tpu_pump_drivers_total",
                       status="pump") > n0


def _run_suite(names, pump: bool):
    from presto_tpu.tools.verifier import load_suite
    suite = load_suite("tpch")
    driver_mod.set_pump(pump)
    r = LocalRunner("tpch", "tiny", properties=dict(NO_CACHE))
    return {n: r.execute(suite[n]).rows() for n in names}


def test_pump_identity_serving_mix(pump_state):
    """The serving mix answers byte-identically pump-on vs pump-off,
    and the on-run really engaged the pump."""
    from presto_tpu.tools.serving_bench import DEFAULT_MIX
    off = _run_suite(DEFAULT_MIX, pump=False)
    n0 = METRICS.get("presto_tpu_pump_drivers_total", status="pump")
    on = _run_suite(DEFAULT_MIX, pump=True)
    assert _pumped(n0), "no driver took the pump fast path"
    assert on == off


def test_pump_join_and_sort_pipelines_pump(pump_state):
    """Join builds, probe chains, and sort-terminated pipelines are
    all in the widened streamable set: a join + ORDER BY query runs
    every one of its drivers through the pump, byte-identically."""
    driver_mod.set_pump(False)
    r = LocalRunner("tpch", "tiny", NO_CACHE)
    expected = r.execute(SQL_JOIN).rows()
    driver_mod.set_pump(True)
    n_pump0 = METRICS.get("presto_tpu_pump_drivers_total",
                          status="pump")
    n_step0 = METRICS.get("presto_tpu_pump_drivers_total",
                          status="step")
    r2 = LocalRunner("tpch", "tiny", NO_CACHE)
    assert r2.execute(SQL_JOIN).rows() == expected
    assert METRICS.get("presto_tpu_pump_drivers_total",
                       status="pump") > n_pump0
    assert METRICS.get("presto_tpu_pump_drivers_total",
                       status="step") == n_step0, \
        "a driver shape in the join query declined the pump"


@pytest.mark.slow
def test_pump_identity_full_tpch(pump_state):
    """The whole TPC-H suite pump-on vs pump-off (the slow lane's
    exhaustive byte-identity sweep)."""
    from presto_tpu.tools.verifier import load_suite
    names = sorted(load_suite("tpch"))
    off = _run_suite(names, pump=False)
    on = _run_suite(names, pump=True)
    for n in names:
        assert on[n] == off[n], n


def test_pump_zero_new_kernels(pump_state):
    """The zero-new-kernels oracle: every kernel family the pump-on
    run compiles was already minted by the pump-off run — the pump
    must never change WHAT is computed, only when batches move."""
    from presto_tpu.tools.serving_bench import DEFAULT_MIX
    _run_suite(DEFAULT_MIX, pump=False)
    fam_off = set(METRICS.by_label(
        "presto_tpu_kernel_compiles_total", "kernel"))
    before = METRICS.by_label(
        "presto_tpu_kernel_compiles_total", "kernel")
    _run_suite(DEFAULT_MIX, pump=True)
    fresh = set(METRICS.delta_by_label(
        "presto_tpu_kernel_compiles_total", "kernel", before))
    assert fresh <= fam_off, f"pump minted new kernels: {fresh - fam_off}"


def _arm_stall(delay_s=0.05):
    from presto_tpu.execution import faults

    def sleeper(ctx):
        time.sleep(delay_s)
        return False
    return faults.arm("operator.add_input", trigger="always",
                      predicate=sleeper)


def test_pump_cancel_lands_mid_pump(pump_state, small_executor):
    """Cancel flips while the pump is streaming splits: the quantum
    checkpoint surfaces kind="cancelled" (the pump honors quanta, it
    does not run the source dry in one sitting)."""
    from presto_tpu.execution import faults
    driver_mod.set_pump(True)
    flag = threading.Event()
    r = LocalRunner("tpch", "tiny", properties=dict(SLOW_PROPS))
    r.execute(SQL_AGG)  # warm kernels: the cancel run is all drive
    _arm_stall(0.05)
    try:
        n0 = METRICS.get("presto_tpu_pump_drivers_total",
                         status="pump")
        timer = threading.Timer(0.15, flag.set)
        timer.start()
        with pytest.raises(QueryError) as ei:
            r.execute(SQL_AGG, cancel=flag.is_set)
        assert ei.value.kind == "cancelled"
        assert _pumped(n0)
    finally:
        timer.cancel()
        faults.disarm()


def test_pump_deadline_lands_mid_pump(pump_state, small_executor):
    """query_max_run_time_ms expires mid-pump -> structured
    deadline_exceeded within a few quanta."""
    from presto_tpu.execution import faults
    driver_mod.set_pump(True)
    _arm_stall(0.05)
    try:
        r = LocalRunner("tpch", "tiny", properties={
            **SLOW_PROPS, "query_max_run_time_ms": 150})
        t0 = time.monotonic()
        with pytest.raises(QueryError) as ei:
            r.execute(SQL_AGG)
        assert ei.value.kind == "deadline_exceeded"
        assert time.monotonic() - t0 < 30.0
    finally:
        faults.disarm()


def test_pump_chaos_quantum_site(pump_state, small_executor):
    """The `executor.quantum` chaos site fires under a pumping driver
    and fails the query cleanly; the executor survives and the next
    statement answers byte-identically to pump-off."""
    from presto_tpu.execution import faults
    driver_mod.set_pump(False)
    r = LocalRunner("tpch", "tiny", properties=dict(SLOW_PROPS))
    expected = r.execute(SQL_AGG).rows()
    driver_mod.set_pump(True)
    inj = faults.arm("executor.quantum", trigger="nth", n=3)
    _arm_stall(0.02)
    try:
        with pytest.raises(faults.InjectedFault):
            r.execute(SQL_AGG)
        assert inj.fired == 1
        faults.disarm()
        assert r.execute(SQL_AGG).rows() == expected
        snap = small_executor.snapshot()
        assert snap["tasks"] == 0 and snap["running_drivers"] == 0
    finally:
        faults.disarm()
