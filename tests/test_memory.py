"""Memory accounting + grouped (bucket-wise) execution (reference:
memory/MemoryPool.java reserve/free, execution/Lifespan.java driver
groups, and the spill tier swap: host RAM plays the role of disk)."""

import jax
import pytest


def test_pool_reserve_free_peak():
    from presto_tpu.execution.memory import (
        MemoryLimitExceeded, MemoryPool,
    )
    p = MemoryPool(1000)
    p.reserve("a", 400)
    p.reserve("b", 500)
    assert p.reserved == 900 and p.peak == 900
    with pytest.raises(MemoryLimitExceeded):
        p.reserve("c", 200)
    p.free_all("a")
    p.reserve("c", 200)
    assert p.reserved == 700
    assert p.peak_by_tag["b"] == 500


def test_local_query_respects_budget():
    from presto_tpu.runner import LocalRunner, QueryError
    r = LocalRunner("tpch", "tiny",
                    {"hbm_budget_bytes": 10_000})  # absurdly small
    with pytest.raises(QueryError, match="memory budget exceeded"):
        r.execute("select * from lineitem order by orderkey")
    # untouched runs still work with a sane budget
    r2 = LocalRunner("tpch", "tiny",
                     {"hbm_budget_bytes": 2_000_000_000})
    assert r2.execute("select count(*) from lineitem").rows()


def test_accounting_in_explain_analyze():
    from presto_tpu.runner import LocalRunner
    r = LocalRunner("tpch", "tiny")
    # full ORDER BY (not TopN): the sort accumulates its input
    res = r.execute("explain analyze select * from lineitem "
                    "order by extendedprice desc")
    text = "\n".join(row[0] for row in res.rows())
    assert "peak mem:" in text
    assert "peak reserved device memory:" in text


@pytest.mark.slow
def test_grouped_execution_under_budget():
    """A partitioned-join query whose shuffled working set exceeds the
    budget re-runs bucket-wise (lifespans) and still matches the
    unconstrained answer."""
    from presto_tpu.runner import MeshRunner
    sql = ("select o.orderpriority, count(*) c, sum(l.quantity) q "
           "from orders o join lineitem l on l.orderkey = o.orderkey "
           "group by o.orderpriority order by o.orderpriority")
    free = MeshRunner("tpch", "tiny",
                      {"broadcast_join_threshold_rows": 0},
                      n_workers=4)
    want = free.execute(sql).rows()
    jax.clear_caches()
    tight = MeshRunner(
        "tpch", "tiny",
        {"broadcast_join_threshold_rows": 0,
         # enough for scans/partials, too small for the whole shuffled
         # join working set at once
         "hbm_budget_bytes": 1_500_000},
        n_workers=4)
    got = tight.execute(sql).rows()
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert abs(g[2] - w[2]) < 1e-6 * max(abs(w[2]), 1)
    jax.clear_caches()


@pytest.mark.slow
def test_spool_spills_to_disk():
    """With a zero host-spool budget every later-lifespan batch takes
    the disk tier (compressed pages via the native codec; reference:
    FileSingleStreamSpiller) and results still match; spill files are
    deleted as buckets reload."""
    import glob
    import tempfile
    from presto_tpu.runner import MeshRunner
    sql = ("select c.nationkey, count(*) n "
           "from customer c join orders o on o.custkey = c.custkey "
           "group by c.nationkey order by c.nationkey")
    pattern = tempfile.gettempdir() + "/presto-tpu-spill-*"
    before = set(glob.glob(pattern))
    plain = MeshRunner("tpch", "tiny",
                       {"broadcast_join_threshold_rows": 0},
                       n_workers=4).execute(sql).rows()
    jax.clear_caches()
    spilly = MeshRunner("tpch", "tiny",
                        {"broadcast_join_threshold_rows": 0,
                         "lifespans": 4, "host_spool_bytes": 0},
                        n_workers=4)
    got = spilly.execute(sql).rows()
    assert got == plain
    assert spilly._last_spilled_pages > 0
    # only compare against OUR run's dirs: stale/concurrent spill
    # dirs from other processes must not flake this test
    leftover = set(glob.glob(pattern)) - before
    assert not leftover, leftover
    jax.clear_caches()


@pytest.mark.slow
def test_manual_lifespans_match():
    """Explicit lifespans (no budget pressure) produce identical
    results — the bucket split is a pure partition of the hash space."""
    from presto_tpu.runner import MeshRunner
    sql = ("select c.nationkey, count(*) n, sum(o.totalprice) s "
           "from customer c join orders o on o.custkey = c.custkey "
           "group by c.nationkey order by c.nationkey")
    plain = MeshRunner("tpch", "tiny",
                       {"broadcast_join_threshold_rows": 0},
                       n_workers=4).execute(sql).rows()
    jax.clear_caches()
    grouped = MeshRunner("tpch", "tiny",
                         {"broadcast_join_threshold_rows": 0,
                          "lifespans": 4},
                         n_workers=4).execute(sql).rows()
    assert len(plain) == len(grouped)
    for p, g in zip(plain, grouped):
        assert p[0] == g[0] and p[1] == g[1]
        # float sums accumulate in a different order across buckets
        assert abs(p[2] - g[2]) < 1e-6 * max(abs(p[2]), 1)
    jax.clear_caches()


def test_join_build_spill_completes_without_restart():
    """Memory revocation (reference: MemoryRevokingScheduler +
    HashBuilderOperator SPILLING_INPUT): a join whose build side
    exceeds the budget must spill build partitions to host RAM and
    COMPLETE — no QueryError, no bucket-wise re-run — with spill
    counters visible in EXPLAIN ANALYZE."""
    from presto_tpu.runner import LocalRunner
    sql = ("select o.orderpriority, count(*) c, sum(l.quantity) q "
           "from orders o join lineitem l on l.orderkey = o.orderkey "
           "group by o.orderpriority order by o.orderpriority")
    free = LocalRunner("tpch", "tiny", {"batch_rows": 2048})
    want = free.execute(sql).rows()
    jax.clear_caches()
    # too small for the whole build side at once, big enough for one
    # streaming batch + the restored 1/8 partitions
    tight = LocalRunner("tpch", "tiny", {"batch_rows": 2048,
                                         "hbm_budget_bytes": 100_000})
    got = tight.execute(sql).rows()
    assert got == want
    res = tight.execute("explain analyze " + sql)
    text = "\n".join(row[0] for row in res.rows())
    assert "spilled:" in text, text
    jax.clear_caches()


def test_agg_partials_spill_under_budget():
    """Sort-path aggregation partials revoke to host RAM under
    pressure; the tree merge restores them FANIN at a time and the
    result matches the unconstrained run."""
    from presto_tpu.runner import LocalRunner
    sql = ("select orderkey, count(*) c, sum(quantity) q "
           "from lineitem group by orderkey "
           "order by q desc, orderkey limit 10")
    free = LocalRunner("tpch", "tiny", {"batch_rows": 4096})
    want = free.execute(sql).rows()
    jax.clear_caches()
    tight = LocalRunner("tpch", "tiny",
                        {"batch_rows": 4096,
                         "hbm_budget_bytes": 3_000_000})
    got = tight.execute(sql).rows()
    assert got == want
    jax.clear_caches()
