"""Kernel contract checker: engineered-violation fixtures per
contract, the all-families clean gate, and the predicted-vs-live
compile-count cross-check (docs/KERNEL_CONTRACTS.md).

Everything except the serving-mix cross-check is pure in-process
tracing over ShapeDtypeStruct inputs — no data, no compiles, no
subprocess workers (tier-1 budget)."""

import json

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from presto_tpu.analysis import runtime as art  # noqa: E402
from presto_tpu.analysis import taint  # noqa: E402
from presto_tpu.analysis.checker import (  # noqa: E402
    RULES, check_contract, check_families, coverage_findings,
    load_contract_modules, registered_families,
)
from presto_tpu.analysis.contracts import (  # noqa: E402
    KernelContract, TracePoint, abstract_batch, all_contracts, sds,
)
from presto_tpu.analysis.expr_types import check_expression  # noqa: E402
from presto_tpu.batch import Batch, Column  # noqa: E402
from presto_tpu.tools.kernelcheck import (  # noqa: E402
    BASELINE_DEFAULT, changed_families, diff_baseline, load_baseline,
    main, write_baseline,
)
from presto_tpu.types import BIGINT, BOOLEAN, DOUBLE, REAL  # noqa: E402


def _schema():
    return [("k", BIGINT), ("v", DOUBLE)]


def _contract(build, **kw):
    kw.setdefault("family", "fixture")
    kw.setdefault("module", "tests.fixture")
    return KernelContract(build=build, **kw)


def _findings(build, **kw):
    findings, _ = check_contract(_contract(build, **kw))
    return findings


# ---------------------------------------------------------------------------
# engineered violations: each contract catches its fixture


def test_pad_leak_is_caught_with_eqn_attribution():
    """The canonical leak: an unmasked sum over padded width."""
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())

        def leaky(batch):
            return jnp.sum(batch.columns["v"].data)
        return TracePoint(leaky, (b,), (rb,))

    found = _findings(build)
    kc1 = [f for f in found if f.rule == "KC001"]
    assert kc1, found
    # eqn-level attribution: the offending primitive and its source
    # line both surface in the finding
    assert "reduce_sum" in kc1[0].message
    assert "test_kernelcheck.py" in kc1[0].source


def test_masked_sum_is_clean():
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())

        def ok(batch):
            c = batch.columns["v"]
            return jnp.sum(jnp.where(c.mask & batch.row_valid,
                                     c.data, 0.0))
        return TracePoint(ok, (b,), (rb,))

    assert not _findings(build)


def test_pad_leak_via_sort_key_is_caught():
    """Sorting by a raw (un-canonicalized) column reorders live rows
    by dead-lane garbage."""
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())

        def leaky(batch):
            d = batch.columns["k"].data
            return jax.lax.sort((d, batch.row_valid), num_keys=1)
        return TracePoint(leaky, (b,), (rb,))

    kc1 = [f for f in _findings(build) if f.rule == "KC001"]
    assert kc1 and "sort" in kc1[0].message


def test_shape_branching_kernel_fails_structure_check():
    """A kernel whose trace-time Python branches on the bucket size
    emits structurally different programs per bucket."""
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())

        def forked(batch):
            d = batch.columns["v"].data
            m = batch.columns["v"].mask & batch.row_valid
            x = jnp.where(m, d, 0.0)
            if cap > 8192:  # the engineered trace-time fork
                x = x * 2.0 + 1.0
            return jnp.sum(x)
        return TracePoint(forked, (b,), (rb,))

    kc2 = [f for f in _findings(build) if f.rule == "KC002"]
    assert any("structure varies across bucket sizes" in f.message
               for f in kc2), kc2


def test_value_baking_kernel_fails_variant_stability():
    """A LIMIT-style operand baked into the trace as a Python constant
    mints one compile per value — the compile-wall class."""
    def build(cap, variant):
        n = variant["n"]  # baked: never passed as an operand
        b, rb = abstract_batch(cap, _schema())

        def baked(batch):
            keep = jnp.arange(cap) < n
            return batch.row_valid & keep
        return TracePoint(baked, (b,), (rb,))

    found = _findings(build, variants=({"n": 10}, {"n": 50}))
    kc2 = [f for f in found if f.rule == "KC002"]
    assert any("baked into the trace" in f.message for f in kc2), found


def test_host_callback_kernel_fails_purity():
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())

        def impure(batch):
            s = jnp.sum(jnp.where(batch.row_valid,
                                  batch.columns["v"].data, 0.0))
            jax.debug.print("total={s}", s=s)
            return s
        return TracePoint(impure, (b,), (rb,))

    kc3 = [f for f in _findings(build) if f.rule == "KC003"]
    assert kc3, "host callback not caught"


def test_promoting_kernel_fails_dtype_stability():
    """An f32 column whose kernel emits f64 (the silent promotion
    class: schema says REAL, exchange pays DOUBLE)."""
    def build(cap, variant):
        b, rb = abstract_batch(cap, [("x", REAL)])

        def promoting(batch):
            c = batch.columns["x"]
            # the promotion: arithmetic in f64, dtype not restored
            d = c.data.astype(jnp.float64) * 2.0
            return Batch({"x": Column(d, c.mask, REAL, None)},
                         batch.row_valid)
        return TracePoint(promoting, (b,), (rb,))

    kc4 = [f for f in _findings(build) if f.rule == "KC004"]
    assert kc4 and "float64" in kc4[0].message


def test_ladder_budget_violation():
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())
        return TracePoint(lambda batch: batch.row_valid, (b,), (rb,))

    found = _findings(build, ladder_budget=1)
    assert any(f.rule == "KC002" and "ladder budget" in f.message
               for f in found)


def test_contract_suppression_is_reasoned():
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())
        return TracePoint(
            lambda batch: jnp.sum(batch.columns["v"].data),
            (b,), (rb,))

    c = _contract(build, suppress=(("KC001", "fixture: deliberate"),))
    findings, _ = check_contract(c)
    assert findings and all(f.suppressed for f in findings
                            if f.rule == "KC001")
    with pytest.raises(ValueError):
        _contract(build, structure_varies=True)  # reason required


# ---------------------------------------------------------------------------
# the tier gate: every registered family, >= 3 ladder buckets, clean


def test_all_families_clean_gate():
    result = check_families()
    assert not result.errors, result.errors
    new, _ = diff_baseline(result.findings,
                           load_baseline(BASELINE_DEFAULT))
    assert not new, "new kernel-contract findings (fix, suppress " \
        "with a reason on the contract, or re-baseline):\n" \
        + "\n".join(f.render() for f in new)
    # the checked-in baseline ships EMPTY: deviations live as
    # reasoned suppressions on the contracts, never as baseline debt
    assert load_baseline(BASELINE_DEFAULT) == {}
    # >= 3 ladder points per contract is the acceptance bar
    for fam, contracts in all_contracts().items():
        for c in contracts:
            assert len(c.buckets) >= 3, (fam, c.buckets)


def test_every_registered_family_has_a_contract():
    load_contract_modules()
    missing = registered_families() - set(all_contracts())
    assert not missing, missing
    assert not coverage_findings()


def test_rule_catalogue():
    assert set(RULES) == {"KC001", "KC002", "KC003", "KC004", "KC005"}


# ---------------------------------------------------------------------------
# baseline + CLI workflow (same contract as tools/lint.py)


def test_baseline_roundtrip(tmp_path):
    def build(cap, variant):
        b, rb = abstract_batch(cap, _schema())
        return TracePoint(
            lambda batch: jnp.sum(batch.columns["v"].data),
            (b,), (rb,))

    findings = _findings(build)
    assert findings
    path = str(tmp_path / "baseline.json")
    write_baseline(path, findings)
    loaded = load_baseline(path)
    assert sum(loaded.values()) == len(findings)
    new, stale = diff_baseline(findings, loaded)
    assert not new and not stale
    new, stale = diff_baseline([], loaded)
    assert not new and stale


def test_cli_surfaces():
    assert main(["--list-rules"]) == 0
    assert main(["--list-families"]) == 0
    assert main(["--family", "limit", "--family", "sort"]) == 0
    assert main(["--all", "--baseline"]) == 0
    assert main(["--family", "no_such_family"]) == 2


def test_cli_json(capsys):
    assert main(["--family", "limit", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["findings"] == []
    assert out["predicted_compiles"]["limit"] >= 3


def test_changed_families_scoped():
    load_contract_modules()
    fams = changed_families("HEAD")
    assert isinstance(fams, list)
    for f in fams:
        assert f in all_contracts()


# ---------------------------------------------------------------------------
# expression-IR type checker (the planner/validation satellite)


def _ill_typed_and():
    from presto_tpu.expr import ir
    return ir.SpecialForm(
        "and", (ir.ref("x", BIGINT), ir.lit(True, BOOLEAN)), BOOLEAN)


def test_expr_types_boolean_context():
    errs = check_expression(_ill_typed_and())
    assert errs and "boolean context" in errs[0]


def test_expr_types_incomparable_comparison():
    from presto_tpu.expr import ir
    from presto_tpu.types import VARCHAR
    e = ir.call("less_than", BOOLEAN, ir.ref("x", BIGINT),
                ir.ref("s", VARCHAR))
    errs = check_expression(e)
    assert errs and "incomparable" in errs[0]


def test_expr_types_arithmetic_over_boolean():
    from presto_tpu.expr import ir
    e = ir.call("add", BIGINT, ir.ref("b", BOOLEAN),
                ir.lit(1, BIGINT))
    assert check_expression(e)


def test_expr_types_clean_expressions_pass():
    from presto_tpu.expr import ir
    e = ir.and_(
        ir.call("less_than", BOOLEAN, ir.ref("x", BIGINT),
                ir.lit(7, BIGINT)),
        ir.SpecialForm("is_null", (ir.ref("y", DOUBLE),), BOOLEAN))
    assert not check_expression(e)
    # UNKNOWN (bare NULL) coerces everywhere
    from presto_tpu.types import UNKNOWN
    e2 = ir.and_(ir.lit(None, UNKNOWN), ir.lit(True, BOOLEAN))
    assert not check_expression(e2)


def test_plan_checker_names_ill_typed_expression():
    from presto_tpu.planner import nodes as N
    from presto_tpu.planner.validation import (
        CHECKER, PlanValidationError,
    )
    src = N.ValuesNode(rows=[[1]],
                       output=(N.Field("x", BIGINT),))
    proj = N.ProjectNode(
        source=src, assignments=[("p", _ill_typed_and())],
        output=(N.Field("p", BOOLEAN),))
    with pytest.raises(PlanValidationError) as ei:
        CHECKER.check_plan(proj, "fixture-pass")
    assert any(v.rule == "expr-type" for v in ei.value.violations)


# ---------------------------------------------------------------------------
# predicted-vs-live compile-count cross-check on the serving mix


def test_predicted_vs_live_compiles_on_serving_mix():
    """The runtime half of KC002: warm the serving mix, then re-run
    it (with DIFFERENT LIMIT constants) under signature tracking. The
    contracts say every fresh trace is a new input signature; on the
    warm pass the signatures repeat, so the live retrace delta must
    be ZERO — any fresh trace is an undeclared retrace source
    (value-baking, dtype drift) and fails the gate."""
    from presto_tpu.runner.local import LocalRunner
    from tpch_queries import QUERIES

    r = LocalRunner("tpch", "tiny", properties={
        "plan_cache_enabled": False,
        "fragment_result_cache_enabled": False,
        "page_source_cache_enabled": False,
    })
    mix = [QUERIES[6],
           "SELECT orderkey, quantity FROM lineitem "
           "WHERE quantity > 30 LIMIT 10"]
    for sql in mix:
        r.execute(sql)

    snap = art.begin_tracking()
    try:
        res = None
        for sql in mix:
            res = r.execute(sql.replace("LIMIT 10", "LIMIT 77"))
        report = art.cross_check(snap, disarm=False)
        # prediction/reality: no family may retrace beyond its
        # observed distinct signatures...
        assert not report["divergent"], report
        # ...and on a WARM mix the delta is exactly zero — LIMIT 77
        # shares every compiled kernel with LIMIT 10 (the PR 6
        # operand-bucketing invariant, now cross-checked live)
        assert art.live_retraces(snap) == {}, art.live_retraces(snap)
        # the fusion report surfaces the per-family prediction
        assert res is not None
        fams = (res.fusion_report or {}).get("kernel_families")
        assert fams, "kernel_families missing from fusion report"
        assert all(n >= 1 for n in fams.values())
    finally:
        from presto_tpu.telemetry import kernels
        kernels.arm_signature_tracking(False)


# ---------------------------------------------------------------------------
# taint interpreter unit coverage (the idiom rules the kernels rely on)


def test_taint_polarity_rules():
    cap = 4096
    b, rb = abstract_batch(cap, _schema())

    def kernel(batch):
        c = batch.columns["v"]
        neutral = jnp.where(c.mask, c.data, 0.0)       # select kill
        narrowed = batch.row_valid & (c.data > 0)      # and kill
        return neutral, narrowed

    closed = jax.make_jaxpr(kernel)(b)
    avs = [taint.av_for_role(r)
           for r in jax.tree_util.tree_leaves(rb)]
    outs, leaks = taint.analyze(closed, avs)
    assert not leaks
    assert all(o.taint == taint.CLEAN for o in outs)


def test_taint_unknown_primitive_is_loud():
    """A primitive without a transfer rule over tainted operands must
    fail closed, not pass silently."""
    cap = 4096
    b, rb = abstract_batch(cap, [("x", DOUBLE)])

    def kernel(batch):
        # fft has (deliberately) no transfer rule
        return jnp.fft.fft(batch.columns["x"].data).real

    closed = jax.make_jaxpr(kernel)(b)
    avs = [taint.av_for_role(r)
           for r in jax.tree_util.tree_leaves(rb)]
    outs, leaks = taint.analyze(closed, avs)
    assert leaks and any("no transfer rule" in l.detail
                         for l in leaks)
    assert any(o.taint == taint.POISON for o in outs)
