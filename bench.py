"""Headline benchmark: TPC-H Q1 end-to-end through the SQL engine.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

Q1 is the reference's own canonical operator benchmark
(presto-benchmark HandTpchQuery1.java — scan + filter + project +
hash aggregation over lineitem), run here through the full stack:
parse -> analyze -> plan -> optimize -> jit'd XLA kernels.

vs_baseline is rows/sec relative to JAVA_BASELINE_ROWS_PER_SEC, an
estimate of the single-node Java operator pipeline on Q1 (the reference
publishes no absolute numbers — BASELINE.md; the estimate is the
HandTpchQuery1 class of result on one modern core, ~10M rows/s).

Methodology: the reported number is the WARM rows/s — timed runs follow
a warmup that compiles the kernels and populates the connector's
device-batch scan cache, so data generation and host->device transfer
are excluded (the Java baseline likewise excludes data-load: the
reference's benchmark pre-loads pages via LocalQueryRunner before
timing). The cold (first-run) time is printed to stderr for reference.

Robustness: the actual run happens in a CHILD process under a hard
subprocess timeout — backend init through the remote TPU tunnel can
hang inside native plugin-discovery code where no in-process deadline
(signal/alarm) can interrupt it. If the native-backend child fails or
hangs, a CPU child (axon sitecustomize bypassed) runs instead, so one
JSON line is ALWAYS emitted.
"""

import json
import os
import subprocess
import sys
import time
import traceback

SCHEMA = "sf1"          # 6,001,215 lineitem rows at SF1 scaling
BATCH_ROWS = 1 << 20
JAVA_BASELINE_ROWS_PER_SEC = 1.0e7
METRIC = f"tpch_q1_{SCHEMA}_rows_per_sec"
CHILD_TIMEOUT_S = 2400

Q1 = """
select returnflag, linestatus,
       sum(quantity) as sum_qty,
       sum(extendedprice) as sum_base_price,
       sum(extendedprice * (1 - discount)) as sum_disc_price,
       sum(extendedprice * (1 - discount) * (1 + tax)) as sum_charge,
       avg(quantity) as avg_qty,
       avg(extendedprice) as avg_price,
       avg(discount) as avg_disc,
       count(*) as count_order
from lineitem
where shipdate <= date '1998-09-02'
group by returnflag, linestatus
order by returnflag, linestatus
"""


def _run_bench() -> float:
    """Execute warm Q1 runs; returns rows/sec."""
    from presto_tpu.runner import LocalRunner

    runner = LocalRunner("tpch", SCHEMA)
    runner.session.properties["batch_rows"] = BATCH_ROWS
    conn = runner.catalogs.connector("tpch")
    gen = conn._gens[SCHEMA]
    import numpy as np
    # actual lineitem cardinality (rows("lineitem") is the order count;
    # each order expands to 1-7 lines)
    n_rows = int(gen.line_counts(
        np.arange(gen.rows("orders")) + 1).sum())

    t0 = time.perf_counter()
    result = runner.execute(Q1)          # warmup: compile + first run
    print(f"cold (compile + datagen + transfer): "
          f"{time.perf_counter() - t0:.3f}s", file=sys.stderr)
    assert len(result.rows()) == 4, result.rows()

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        runner.execute(Q1)
        times.append(time.perf_counter() - t0)
        print(f"run: {times[-1]:.3f}s", file=sys.stderr)
    best = min(times)
    return n_rows / best


def _emit(rows_per_sec: float, **extra) -> None:
    line = {
        "metric": METRIC,
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / JAVA_BASELINE_ROWS_PER_SEC, 4),
    }
    line.update(extra)
    print(json.dumps(line))


def _child_main() -> int:
    """Run the bench in this process and print the JSON line."""
    try:
        rows_per_sec = _run_bench()
    except Exception:  # noqa: BLE001 - always emit the JSON line
        traceback.print_exc()
        _emit(0.0, error=traceback.format_exc(limit=3)[-500:])
        return 1
    extra = {}
    if os.environ.get("PRESTO_TPU_BENCH_PLATFORM"):
        extra["platform"] = os.environ["PRESTO_TPU_BENCH_PLATFORM"]
    _emit(rows_per_sec, **extra)
    return 0


def main() -> int:
    if os.environ.get("PRESTO_TPU_BENCH_CHILD") == "1":
        return _child_main()

    attempts = [
        ("native", {}),
        # the axon plugin sitecustomize (PYTHONPATH) can hang discovery
        # even when cpu is selected — clear it for the fallback child
        ("cpu_fallback", {"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                          "PRESTO_TPU_BENCH_PLATFORM": "cpu_fallback"}),
    ]
    for name, env_mod in attempts:
        env = {**os.environ, **env_mod, "PRESTO_TPU_BENCH_CHILD": "1"}
        print(f"bench attempt: {name}", file=sys.stderr)
        # cheap probe child first: a wedged TPU tunnel hangs inside
        # native plugin discovery; bound that to 300s instead of a full
        # bench timeout
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "jnp.zeros(()).block_until_ready(); "
                 "print(jax.default_backend())"],
                env=env, timeout=300, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"backend probe for {name} hung (300s); skipping",
                  file=sys.stderr)
            continue
        if probe.returncode != 0:
            print(f"backend probe for {name} failed:\n"
                  f"{probe.stderr[-1500:]}", file=sys.stderr)
            continue
        print(f"backend: {probe.stdout.strip()}", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                timeout=CHILD_TIMEOUT_S, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"bench attempt {name} timed out after "
                  f"{CHILD_TIMEOUT_S}s", file=sys.stderr)
            continue
        sys.stderr.write(proc.stderr[-4000:])
        json_lines = [l for l in proc.stdout.splitlines()
                      if l.startswith("{")]
        if proc.returncode == 0 and json_lines:
            print(json_lines[-1])
            return 0
        print(f"bench attempt {name} failed (rc={proc.returncode})",
              file=sys.stderr)
    _emit(0.0, error="all bench attempts failed or timed out")
    return 0


if __name__ == "__main__":
    sys.exit(main())
