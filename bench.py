"""Headline benchmark: TPC-H Q1 end-to-end through the SQL engine.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

Q1 is the reference's own canonical operator benchmark
(presto-benchmark HandTpchQuery1.java — scan + filter + project +
hash aggregation over lineitem), run here through the full stack:
parse -> analyze -> plan -> optimize -> jit'd XLA kernels.

vs_baseline is rows/sec relative to JAVA_BASELINE_ROWS_PER_SEC, an
estimate of the single-node Java operator pipeline on Q1 (the reference
publishes no absolute numbers — BASELINE.md; the estimate is the
HandTpchQuery1 class of result on one modern core, ~10M rows/s).
"""

import json
import sys
import time

SCHEMA = "sf1"          # 6,001,215 lineitem rows at SF1 scaling
BATCH_ROWS = 1 << 20
JAVA_BASELINE_ROWS_PER_SEC = 1.0e7

Q1 = """
select returnflag, linestatus,
       sum(quantity) as sum_qty,
       sum(extendedprice) as sum_base_price,
       sum(extendedprice * (1 - discount)) as sum_disc_price,
       sum(extendedprice * (1 - discount) * (1 + tax)) as sum_charge,
       avg(quantity) as avg_qty,
       avg(extendedprice) as avg_price,
       avg(discount) as avg_disc,
       count(*) as count_order
from lineitem
where shipdate <= date '1998-09-02'
group by returnflag, linestatus
order by returnflag, linestatus
"""


def main() -> None:
    from presto_tpu.runner import LocalRunner

    runner = LocalRunner("tpch", SCHEMA)
    runner.session.properties["batch_rows"] = BATCH_ROWS
    conn = runner.catalogs.connector("tpch")
    gen = conn._gens[SCHEMA]
    import numpy as np
    # actual lineitem cardinality (rows("lineitem") is the order count;
    # each order expands to 1-7 lines)
    n_rows = int(gen.line_counts(
        np.arange(gen.rows("orders")) + 1).sum())

    result = runner.execute(Q1)          # warmup: compile + first run
    assert len(result.rows()) == 4, result.rows()

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        runner.execute(Q1)
        times.append(time.perf_counter() - t0)
        print(f"run: {times[-1]:.3f}s", file=sys.stderr)
    best = min(times)
    rows_per_sec = n_rows / best

    print(json.dumps({
        "metric": f"tpch_q1_{SCHEMA}_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / JAVA_BASELINE_ROWS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
