"""Headline benchmark: TPC-H suite (Q1, Q3, Q5, Q6, Q18) at SF1,
end-to-end through the SQL engine.

Prints ONE json line:
  {"metric", "value", "unit", "vs_baseline", "platform", "suite", ...}

- metric/value/vs_baseline keep the round-comparable headline: Q1
  rows/sec (the reference's canonical operator benchmark,
  presto-benchmark HandTpchQuery1.java — scan + filter + project +
  hash aggregation over lineitem).
- "suite" embeds per-query results: rows/sec (input rows / best warm
  wall), speedup vs the per-query Java estimate, and wall seconds. Q3
  and Q5 exercise the join kernels, Q6 the filter/project path
  (HandTpchQuery6.java), Q18 the high-cardinality (~1.5M groups)
  sort-path aggregation.
- "geomean_vs_baseline" is the geometric mean of the per-query
  speedups (the BASELINE.md north-star shape).

Baseline denominator (VERDICT r3 weak #5/next-step 2): the reference
publishes no absolute numbers and its Java harness cannot run in this
image (no JVM). The denominator is therefore MEASURED by
baseline_proxy.py — the same five queries on the same generated data
through pyarrow's Acero C++ engine — and recorded in
BASELINE_MEASURED.json; the output line carries
"baseline": "measured:pyarrow-acero-<ver>@<schema>". Only if that
file is absent (or was measured at a different schema) does the old
per-query Java ESTIMATE table apply, and the line then says
"baseline": "estimate:java-guess" so nobody mistakes it for data.

Methodology: per query, the reported number is the WARM rows/s — timed
runs follow a warmup that compiles the kernels and populates the
connector's device-batch scan cache, so data generation and
host->device transfer are excluded (the Java baseline likewise
excludes data-load: the reference's benchmarks pre-load pages via
LocalQueryRunner before timing). "rows" is the sum of the base-table
rows the query scans.

Robustness: the actual run happens in a CHILD process under a hard
subprocess timeout — backend init through the remote TPU tunnel can
hang inside native plugin-discovery code where no in-process deadline
(signal/alarm) can interrupt it. If the native-backend child fails or
hangs, a CPU child (axon sitecustomize bypassed) runs instead, so one
JSON line is ALWAYS emitted. A partially-completed suite still emits
whatever queries finished.
"""

import json
import math
import os
import subprocess
import sys
import time
import traceback

SCHEMA = "sf1"          # 6,001,215 lineitem rows at SF1 scaling
BATCH_ROWS = 1 << 20
METRIC = f"tpch_q1_{SCHEMA}_rows_per_sec"
#: per-QUERY child timeout: each query runs in its own subprocess so
#: one wedged tunnel RPC cannot take the rest of the suite with it
#: (the r4 native capture lost Q3-Q18 to exactly that)
QUERY_TIMEOUT_S = 700
#: total wall budget across all children + fallbacks
TOTAL_BUDGET_S = 5000
WARM_RUNS = 2

#: per-query single-node Java estimates (input rows/sec) — the
#: UNMEASURED fallback, used only when BASELINE_MEASURED.json is absent
JAVA_BASELINE = {
    "q1": 1.0e7,
    "q3": 6.0e6,
    "q5": 5.0e6,
    "q6": 2.5e7,
    "q18": 5.0e6,
}


def _load_baseline():
    """(per-query rows/s denominators, label). Prefers the measured
    Acero proxy (baseline_proxy.py) at the bench schema."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    try:
        with open(path) as f:
            m = json.load(f)
        if m.get("schema") != SCHEMA:
            print(f"BASELINE_MEASURED.json schema={m.get('schema')!r} "
                  f"!= bench schema {SCHEMA!r}; falling back to "
                  f"estimates", file=sys.stderr)
        else:
            denom = {q: r["rows_per_sec"]
                     for q, r in m["queries"].items()}
            missing = [q for q in JAVA_BASELINE if q not in denom]
            if not missing:
                label = (f"measured:{m['engine']}-"
                         f"{m['engine_version']}@{m['schema']}")
                return denom, label
            print(f"BASELINE_MEASURED.json missing queries {missing}; "
                  f"falling back to estimates", file=sys.stderr)
    except (OSError, KeyError, json.JSONDecodeError) as e:
        print(f"no usable BASELINE_MEASURED.json ({e}); "
              f"falling back to estimates", file=sys.stderr)
    return dict(JAVA_BASELINE), "estimate:java-guess"


def _queries():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES
    return {f"q{n}": QUERIES[n] for n in (1, 3, 5, 6, 18)}


def _scanned_rows(gen):
    """Base-table cardinalities, then per-query scanned-row totals."""
    import numpy as np
    L = int(gen.line_counts(np.arange(gen.rows("orders")) + 1).sum())
    O = gen.rows("orders")
    C = gen.rows("customer")
    S = gen.rows("supplier")
    return {
        "q1": L,
        "q3": L + O + C,
        "q5": L + O + C + S + 25 + 5,
        "q6": L,
        "q18": 2 * L + O + C,   # lineitem feeds both the HAVING
                                # subquery and the outer join
    }


def _child_main() -> int:
    """Run the selected queries in this process, one JSON line per
    query (the parent aggregates them into the single driver line).
    A query that fails is reported and skipped — later queries still
    run. PRESTO_TPU_BENCH_QUERIES selects a subset (the parent runs
    one query per child so a wedged tunnel RPC only costs that
    query)."""
    from presto_tpu.runner import LocalRunner

    runner = LocalRunner("tpch", SCHEMA)
    runner.session.properties["batch_rows"] = BATCH_ROWS
    # this bench measures KERNEL EXECUTION throughput: the plan and
    # fragment-result caches would make warm runs replay stored
    # batches instead of executing anything. The page-source cache
    # stays ON — it is the successor of the tpch connector's internal
    # device-batch scan cache this methodology always relied on
    # ("warm runs exclude data generation"; serving-path throughput
    # is serving_bench's metric, not this one)
    runner.session.properties["plan_cache_enabled"] = False
    runner.session.properties["fragment_result_cache_enabled"] = False
    rows_of = _scanned_rows(runner.catalogs.connector("tpch")._gens[SCHEMA])

    subset = os.environ.get("PRESTO_TPU_BENCH_QUERIES")
    queries = _queries()
    if subset:
        queries = {q: queries[q] for q in subset.split(",")
                   if q in queries}
    import jax
    from presto_tpu.telemetry.metrics import METRICS
    backend = jax.default_backend()
    ok = True
    for name, sql in queries.items():
        try:
            fam0 = METRICS.by_label(
                "presto_tpu_kernel_compiles_total", "kernel")
            t0 = time.perf_counter()
            result = runner.execute(sql)  # warmup: compile + first run
            nrows = len(result.rows())    # forces the device fetch
            cold = time.perf_counter() - t0
            # whole-fragment fusion coverage of this query (planner
            # pass report; chains fused vs fallen back — see
            # tools/fusion_report.py for the per-fragment detail,
            # embedded wholesale under --fusion-report)
            fr = getattr(result, "fusion_report", None) or {}
            fused_fragments = fr.get("fused", 0)
            fusion_detail = fr if os.environ.get(
                "PRESTO_TPU_BENCH_FUSION") else None
            print(f"{name} cold (compile + datagen + transfer): "
                  f"{cold:.3f}s, {nrows} result rows", file=sys.stderr)
            # adaptive: a slow (CPU-fallback/contended) query gets one
            # warm run so the whole suite fits the driver's budget
            times = []
            for _ in range(1 if cold > 180 else WARM_RUNS):
                t0 = time.perf_counter()
                runner.execute(sql).rows()
                times.append(time.perf_counter() - t0)
                print(f"{name} run: {times[-1]:.3f}s", file=sys.stderr)
            best = min(times)
            # distinct_compiles per kernel family (cold + warm runs):
            # the compile-amortization trajectory, tracked per round
            # like rows/sec (shape bucketing should drive the warm-run
            # share to zero)
            distinct = METRICS.delta_by_label(
                "presto_tpu_kernel_compiles_total", "kernel", fam0)
        except Exception:  # noqa: BLE001 - report, keep going
            ok = False
            traceback.print_exc()
            continue
        line = {"q": name,
                "rows_per_sec": round(rows_of[name] / best, 1),
                "wall_s": round(best, 3),
                "distinct_compiles": distinct,
                "fused_fragments": fused_fragments,
                "backend": backend}
        if fusion_detail is not None:
            line["fusion"] = fusion_detail
        print(json.dumps(line), flush=True)
    return 0 if ok else 1


def _combine(per_query: dict, platform: str) -> dict:
    denom, baseline_label = _load_baseline()
    suite = {}
    speedups = []
    distinct_compiles = {}
    for name, r in per_query.items():
        sp = r["rows_per_sec"] / denom[name]
        suite[name] = {"rows_per_sec": r["rows_per_sec"],
                       "wall_s": r["wall_s"],
                       "vs_baseline": round(sp, 4)}
        if r.get("distinct_compiles"):
            suite[name]["distinct_compiles"] = r["distinct_compiles"]
            for fam, n in r["distinct_compiles"].items():
                distinct_compiles[fam] = \
                    distinct_compiles.get(fam, 0) + n
        if "fused_fragments" in r:
            suite[name]["fused_fragments"] = r["fused_fragments"]
        if "fusion" in r:
            suite[name]["fusion"] = r["fusion"]
        speedups.append(sp)
    q1 = per_query.get("q1", {"rows_per_sec": 0.0})
    line = {
        "metric": METRIC,
        "value": q1["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": round(q1["rows_per_sec"] / denom["q1"], 4),
        "baseline": baseline_label,
        "platform": platform,
        "suite": suite,
        "distinct_compiles": distinct_compiles,
    }
    if speedups:
        line["geomean_vs_baseline"] = round(
            math.exp(sum(math.log(max(s, 1e-9)) for s in speedups)
                     / len(speedups)), 4)
    return line


def _probe(name: str, env: dict) -> bool:
    """Cheap backend probe: a wedged TPU tunnel hangs inside native
    plugin discovery; bound that to 300s instead of a query timeout."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp, numpy as np; "
             "print(np.asarray(jnp.arange(4).sum())); "
             "print(jax.default_backend())"],
            env=env, timeout=300, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"backend probe for {name} hung (300s); skipping",
              file=sys.stderr)
        return False
    if probe.returncode != 0:
        print(f"backend probe for {name} failed:\n"
              f"{probe.stderr[-1500:]}", file=sys.stderr)
        return False
    print(f"{name} backend: "
          f"{probe.stdout.strip().splitlines()[-1]}", file=sys.stderr)
    return True


def _run_one(qname: str, env: dict, timeout_s: float):
    """One query in its own child; returns its result dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**env, "PRESTO_TPU_BENCH_QUERIES": qname},
            timeout=timeout_s, capture_output=True, text=True)
        out, rc = proc.stdout, proc.returncode
        sys.stderr.write(proc.stderr[-2500:])
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        rc = -1
        print(f"{qname} child timed out after {timeout_s:.0f}s",
              file=sys.stderr)
    for ln in out.splitlines():
        if ln.startswith("{"):
            try:
                r = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if r.get("q") == qname:
                return r
    if rc not in (0, -1):
        print(f"{qname} child failed rc={rc}", file=sys.stderr)
    return None


def main() -> int:
    if os.environ.get("PRESTO_TPU_BENCH_CHILD") == "1":
        return _child_main()

    # --fusion-report: embed the per-query whole-fragment fusion
    # coverage (fused chains + fallback reasons, planner/fusion.py) in
    # each suite entry — rides an env var so the per-query children
    # see it too
    if "--fusion-report" in sys.argv[1:]:
        os.environ["PRESTO_TPU_BENCH_FUSION"] = "1"

    deadline = time.time() + TOTAL_BUDGET_S
    attempts = [
        ("native", {}),
        # the axon plugin sitecustomize (PYTHONPATH) can hang discovery
        # even when cpu is selected — clear it for the fallback child
        ("cpu_fallback", {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}),
    ]
    envs = {}
    for name, env_mod in attempts:
        envs[name] = {**os.environ, **env_mod,
                      "PRESTO_TPU_BENCH_CHILD": "1"}
    alive = {name: None for name, _ in attempts}  # None = unprobed

    per_query = {}
    platforms = {}

    def emit() -> None:
        """Print the combined line NOW: the driver takes the last
        JSON line, so emitting after every query guarantees a valid
        (partial) capture even if the whole bench is killed."""
        plats = set(platforms.values())
        platform = plats.pop() if len(plats) == 1 else "mixed"
        line = _combine(per_query, platform)
        if platform == "mixed":
            line["platform_by_query"] = platforms
        print(json.dumps(line), flush=True)
        # every capture containing >= 1 NATIVE query is committed as an
        # artifact the moment it exists (VERDICT r4: "a number that
        # isn't in a committed JSON with platform + timestamp doesn't
        # exist") — bench.py itself only writes the file; committing is
        # the runner's job, but the file survives a crashed run
        native_qs = {q: r for q, r in per_query.items()
                     if platforms.get(q) == "native"}
        if native_qs:
            artifact = dict(line)
            artifact["platform_by_query"] = dict(platforms)
            artifact["captured_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%S%z")
            path = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "BENCH_NATIVE_r05.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=1)

    for qname in _queries():
        for name, _ in attempts:
            left = deadline - time.time()
            if left < 120:
                break
            if alive[name] is None:
                alive[name] = _probe(name, envs[name])
            if not alive[name]:
                continue
            r = _run_one(qname, envs[name],
                         min(QUERY_TIMEOUT_S, left))
            if r is not None:
                per_query[qname] = r
                # the platform label is the child's ACTUAL backend —
                # never the attempt name (the "native" attempt runs on
                # CPU when the environment forces JAX_PLATFORMS=cpu,
                # and a mislabeled capture is an invented number)
                be = r.get("backend", "")
                platforms[qname] = "native" if be == "tpu" \
                    else (be or name)
                emit()
                break
            if name == "native":
                # a wedge mid-query usually means the tunnel needs a
                # re-probe before the next native attempt
                alive[name] = None
        if deadline - time.time() < 120:
            print("bench wall budget exhausted", file=sys.stderr)
            break

    if per_query:
        return 0  # emit() already printed the final combined line
    print(json.dumps({"metric": METRIC, "value": 0.0, "unit": "rows/s",
                      "vs_baseline": 0.0,
                      "error": "all bench attempts failed or timed out"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
