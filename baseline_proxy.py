"""Measured CPU baseline for the bench suite (VERDICT r3 weak #5).

The reference's own harness (presto-benchmark BenchmarkSuite /
HandTpchQuery1, see BASELINE.md) cannot run in this image: there is no
JVM (`which java` -> nothing) and no network egress to fetch one. The
previous rounds therefore compared against hand-invented per-query
"Java estimates" — unfalsifiable numbers. This module replaces them
with a MEASURED proxy: the same five TPC-H queries, on the same
generated data, executed by pyarrow's Acero engine (multithreaded
C++ vectorized execution, the closest thing to a production columnar
CPU engine available in this image). The proxy is deliberately
engine-favourable:

- tables are materialized to Arrow ONCE, untimed (the bench likewise
  excludes datagen/transfer from warm timings);
- dictionary-encoded VARCHAR filters compare int codes, not strings
  (what the Java engine's dictionary blocks do);
- each query gets a warmup run, then best-of-2 timed runs.

Run `python baseline_proxy.py [schema]` to (re)measure and write
BASELINE_MEASURED.json; bench.py loads that file as the denominator
and labels its output "baseline": "measured:pyarrow-acero-<ver>".

Query semantics are pinned by tests/test_baseline_proxy.py, which
cross-checks every proxy query against the SQL engine at sf0_01.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)


def _days(iso: str) -> int:
    y, m, d = map(int, iso.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


def _code(gen, table: str, column: str, value: str) -> int:
    """Dictionary code of `value` in a dict-encoded VARCHAR column."""
    for c in gen.schema(table).columns:
        if c.name == column:
            return list(c.dictionary).index(value)
    raise KeyError(f"{table}.{column}")


def load_tables(gen, names):
    """Materialize tables as pyarrow Tables (dict VARCHARs stay as int
    codes; dates stay as int days) — the same physical shapes the
    engine's scan produces, so neither side pays a decode the other
    doesn't."""
    import pyarrow as pa

    out = {}
    for name in names:
        n = gen.rows(name) if name != "lineitem" else None
        cols = {}
        if name == "lineitem":
            # generate() takes an ORDER range for lineitem (rows
            # expand ~4x per order)
            data = gen.generate("lineitem", 0, gen.rows("orders"))
        else:
            data = gen.generate(name, 0, n)
        for cname, arr in data.items():
            cols[cname] = pa.array(np.ascontiguousarray(arr))
        out[name] = pa.table(cols)
    return out


# --- the five suite queries, Acero-side ---------------------------------

def q1(t, gen):
    import pyarrow.compute as pc

    li = t["lineitem"]
    li = li.filter(pc.less_equal(li["shipdate"], _days("1998-09-02")))
    one_minus = pc.subtract(1.0, li["discount"])
    disc_price = pc.multiply(li["extendedprice"], one_minus)
    charge = pc.multiply(disc_price, pc.add(1.0, li["tax"]))
    li = li.append_column("disc_price", disc_price)
    li = li.append_column("charge", charge)
    res = li.group_by(["returnflag", "linestatus"]).aggregate([
        ("quantity", "sum"), ("extendedprice", "sum"),
        ("disc_price", "sum"), ("charge", "sum"),
        ("quantity", "mean"), ("extendedprice", "mean"),
        ("discount", "mean"), ("quantity", "count"),
    ])
    return res.sort_by([("returnflag", "ascending"),
                        ("linestatus", "ascending")])


def q3(t, gen):
    import pyarrow.compute as pc

    seg = _code(gen, "customer", "mktsegment", "BUILDING")
    cutoff = _days("1995-03-15")
    cust = t["customer"]
    cust = cust.filter(pc.equal(cust["mktsegment"], seg)) \
               .select(["custkey"])
    orders = t["orders"]
    orders = orders.filter(pc.less(orders["orderdate"], cutoff)) \
                   .select(["orderkey", "custkey", "orderdate",
                            "shippriority"])
    orders = orders.join(cust, "custkey", join_type="inner")
    li = t["lineitem"]
    li = li.filter(pc.greater(li["shipdate"], cutoff)) \
           .select(["orderkey", "extendedprice", "discount"])
    j = li.join(orders, "orderkey", join_type="inner")
    rev = pc.multiply(j["extendedprice"],
                      pc.subtract(1.0, j["discount"]))
    j = j.append_column("rev", rev)
    res = j.group_by(["orderkey", "orderdate", "shippriority"]) \
           .aggregate([("rev", "sum")])
    return res.sort_by([("rev_sum", "descending"),
                        ("orderdate", "ascending")]).slice(0, 10)


def q5(t, gen):
    import pyarrow.compute as pc

    asia = _code(gen, "region", "name", "ASIA")
    region = t["region"]
    region = region.filter(pc.equal(region["name"], asia)) \
                   .select(["regionkey"])
    nation = t["nation"].select(["nationkey", "regionkey", "name"]) \
        .join(region, "regionkey", join_type="inner") \
        .select(["nationkey", "name"]) \
        .rename_columns(["nationkey", "n_name"])
    supp = t["supplier"].select(["suppkey", "nationkey"]) \
        .join(nation, "nationkey", join_type="inner")
    cust = t["customer"].select(["custkey", "nationkey"]) \
        .rename_columns(["custkey", "c_nationkey"])
    orders = t["orders"]
    orders = orders.filter(pc.and_(
        pc.greater_equal(orders["orderdate"], _days("1994-01-01")),
        pc.less(orders["orderdate"], _days("1995-01-01")))) \
        .select(["orderkey", "custkey"])
    orders = orders.join(cust, "custkey", join_type="inner") \
        .select(["orderkey", "c_nationkey"])
    li = t["lineitem"].select(
        ["orderkey", "suppkey", "extendedprice", "discount"])
    j = li.join(orders, "orderkey", join_type="inner")
    # c.nationkey = s.nationkey folds into the supplier join keys
    j = j.join(supp, keys=["suppkey", "c_nationkey"],
               right_keys=["suppkey", "nationkey"], join_type="inner")
    rev = pc.multiply(j["extendedprice"],
                      pc.subtract(1.0, j["discount"]))
    j = j.append_column("rev", rev)
    res = j.group_by(["n_name"]).aggregate([("rev", "sum")])
    return res.sort_by([("rev_sum", "descending")])


def q6(t, gen):
    import pyarrow.compute as pc

    li = t["lineitem"]
    m = pc.and_(
        pc.and_(pc.greater_equal(li["shipdate"], _days("1994-01-01")),
                pc.less(li["shipdate"], _days("1995-01-01"))),
        pc.and_(
            pc.and_(pc.greater_equal(li["discount"], 0.05),
                    pc.less_equal(li["discount"], 0.07)),
            pc.less(li["quantity"], 24.0)))
    li = li.filter(m)
    import pyarrow as pa
    s = pc.sum(pc.multiply(li["extendedprice"], li["discount"]))
    return pa.table({"revenue": [s.as_py()]})


def q18(t, gen):
    import pyarrow.compute as pc

    li = t["lineitem"].select(["orderkey", "quantity"])
    big = li.group_by(["orderkey"]).aggregate([("quantity", "sum")])
    big = big.filter(pc.greater(big["quantity_sum"], 300.0)) \
             .select(["orderkey"])
    orders = t["orders"] \
        .select(["orderkey", "custkey", "orderdate", "totalprice"]) \
        .join(big, "orderkey", join_type="inner")
    cust = t["customer"].select(["custkey", "name"])
    orders = orders.join(cust, "custkey", join_type="inner")
    j = li.join(orders, "orderkey", join_type="inner")
    res = j.group_by(["name", "custkey", "orderkey", "orderdate",
                      "totalprice"]).aggregate([("quantity", "sum")])
    return res.sort_by([("totalprice", "descending"),
                        ("orderdate", "ascending")]).slice(0, 100)


QUERIES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6, "q18": q18}
TABLES = ["lineitem", "orders", "customer", "supplier", "nation",
          "region"]


def measure(schema: str = "sf1", runs: int = 2) -> dict:
    import pyarrow

    from presto_tpu.connectors.tpch import TpchGenerator

    sf = {"tiny": 0.001, "sf0_01": 0.01, "sf0_1": 0.1, "sf1": 1.0,
          "sf10": 10.0}[schema]
    gen = TpchGenerator(sf)
    t0 = time.perf_counter()
    tables = load_tables(gen, TABLES)
    print(f"datagen+arrow ({schema}): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    import bench
    rows_of = bench._scanned_rows(gen)

    out = {}
    for name, fn in QUERIES.items():
        fn(tables, gen)  # warmup (plans/kernels/thread pool)
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            res = fn(tables, gen)
            nrows = res.num_rows
            times.append(time.perf_counter() - t0)
        best = min(times)
        out[name] = {"rows_per_sec": round(rows_of[name] / best, 1),
                     "wall_s": round(best, 4), "result_rows": nrows}
        print(f"{name}: best {best:.3f}s "
              f"({out[name]['rows_per_sec']:.3g} rows/s)",
              file=sys.stderr)
    return {
        "engine": "pyarrow-acero",
        "engine_version": pyarrow.__version__,
        "schema": schema,
        "threads": os.cpu_count(),
        "note": ("measured CPU proxy; the reference's Java harness "
                 "cannot run here (no JVM in image) — see BASELINE.md"),
        "queries": out,
    }


def main() -> int:
    schema = sys.argv[1] if len(sys.argv) > 1 else "sf1"
    result = measure(schema)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
    print(f"wrote {path}", file=sys.stderr)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
